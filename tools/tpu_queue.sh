#!/bin/bash
# TPU work queue: poll the tunnel; when it answers, run the round's
# evidence suite sequentially (bench -> kernel profile -> scale run).
# Each stage tees raw stdout/stderr to logs/ (committed — chip evidence
# must never exist only as a transcription); the queue stops polling
# after MAX_WAIT_S without a live backend.
set -u
MAX_WAIT_S=${MAX_WAIT_S:-14400}
POLL_S=${POLL_S:-180}
RTAG=${RTAG:-r04}
cd /root/repo
mkdir -p logs

waited=0
while true; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "$(date -u +%H:%M:%S) tunnel is up" ; break
  fi
  waited=$((waited + POLL_S))
  if [ "$waited" -ge "$MAX_WAIT_S" ]; then
    echo "$(date -u +%H:%M:%S) gave up waiting for tunnel"; exit 2
  fi
  echo "$(date -u +%H:%M:%S) tunnel down; waited ${waited}s"
  sleep "$POLL_S"
done

echo "=== stage 1: bench.py (first number in hand, untuned K) ==="
timeout 5400 python bench.py >"logs/bench_${RTAG}_stage1.log" 2>"logs/bench_${RTAG}_stage1.err"
echo "bench rc=$? ; $(tail -1 "logs/bench_${RTAG}_stage1.log" 2>/dev/null)"

echo "=== stage 2: profile_kernels (chip k-sweep + roofline + trace + sharded collectives) ==="
timeout 7200 python tools/profile_kernels.py >"logs/profile_${RTAG}.log" 2>"logs/profile_${RTAG}.err"
prof_rc=$?
echo "profile rc=$prof_rc"
# regenerate the human-readable evidence tables from PERF.json in the
# same unattended window (no transcription step to lose)
timeout 120 python tools/update_perf_md.py >>"logs/profile_${RTAG}.log" 2>&1
echo "perf_md rc=$?"

# gate on what stage 3 actually consumes: a chip-labeled k-sweep in
# the COMMITTED PERF.json (a CPU-fallback profile writes .partial only
# and still exits 0)
if [ "$prof_rc" -eq 0 ] && grep -q '"backend": "tpu"' PERF.json 2>/dev/null; then
  echo "=== stage 3: bench.py again (now reads the chip-tuned K from PERF.json) ==="
  timeout 5400 python bench.py >"logs/bench_${RTAG}_stage3.log" 2>"logs/bench_${RTAG}_stage3.err"
  echo "bench2 rc=$? ; $(tail -1 "logs/bench_${RTAG}_stage3.log" 2>/dev/null)"
else
  echo "stage 3 skipped: no chip-labeled k-sweep to consume (profile rc=$prof_rc)"
fi

echo "=== stage 4: scale_run (driver+fused on chip, sharded on cpu mesh) ==="
timeout 7200 python tools/scale_run.py >"logs/scale_${RTAG}.log" 2>"logs/scale_${RTAG}.err"
echo "scale rc=$?"
echo "queue done"

#!/bin/bash
# TPU work queue (round 5): poll the tunnel; when it answers, run the
# round's evidence suite in RISK order — insurance bench first, then
# wedge-SAFE profiler sections (ingress A/B, k+chunk sweeps, host
# tiers), then a tuned bench, then the compile-cap probes and
# wedge-prone sections (dense/fused/driver LAST: round 4 lost the
# window's tail to one 2400s wedged compile), then a final bench that
# reads any raised caps, then the scale ladder. The profiler's
# `sharded` section (CPU-mesh collectives; no chip needed) runs at the
# very end so it never competes with chip stages for the host core.
#
# Each stage tees raw stdout/stderr to logs/ AND git-commits the
# evidence immediately — chip numbers must never exist only in a
# process that a dropped tunnel or ended session can take with it.
set -u
MAX_WAIT_S=${MAX_WAIT_S:-39600}
POLL_S=${POLL_S:-120}
RTAG=${RTAG:-r05}
cd /root/repo
mkdir -p logs

log() { echo "$(date -u +%H:%M:%S) $*"; }

commit_evidence() {
  # One `git add` per path, existing paths only: a single atomic add
  # with one missing pathspec (e.g. PERF.json.partial before any
  # profiler run) stages NOTHING and silently skips the checkpoint.
  local p
  for p in logs PERF.json PERF_tpu.json PERF_cpu.json \
           PERF.json.partial PERF.md "BENCH_chip_${RTAG}.json"; do
    [ -e "$p" ] && git add "$p" >/dev/null 2>&1
  done
  # Best-effort: index-lock contention just skips this checkpoint; the
  # next stage commits the same paths.
  git commit -q -m "$1" >/dev/null 2>&1 && log "committed: $1" || true
}

# Collect every chip-backed bench row from this round's stage logs
# into a committed BENCH_chip_<RTAG>.json — the driver's end-of-round
# BENCH_r*.json capture ran against a down tunnel two rounds straight,
# leaving the official artifact CPU-labeled while the real chip ladder
# lived only in logs (VERDICT r4 weak-6).
snapshot_chip_bench() {
  python - "$RTAG" <<'PYEOF'
import json, os, sys
rtag = sys.argv[1]
rows = []
for stage in ("stage1", "stage3", "stage5"):
    p = "logs/bench_%s_%s.log" % (rtag, stage)
    if not os.path.exists(p):
        continue
    for line in open(p):
        try:
            r = json.loads(line)
        except ValueError:
            continue
        if isinstance(r, dict) and "metric" in r \
                and "[CPU" not in r["metric"]:
            r["stage"] = stage
            rows.append(r)
if rows:
    with open("BENCH_chip_%s.json" % rtag, "w") as f:
        json.dump(rows, f, indent=1)
    print("BENCH_chip_%s.json: %d chip rows" % (rtag, len(rows)))
else:
    print("no chip-backed bench rows yet")
PYEOF
}

# fresh_chip_rows STAMP: PERF.json was (re)written after STAMP by a
# profiler run that landed at least one chip-labeled section (flush()
# writes the non-.partial file only then). Guards against gating on
# the committed previous-round PERF.json, which is already tpu-labeled.
fresh_chip_rows() {
  [ PERF.json -nt "$1" ] && grep -q '"backend": "tpu"' PERF.json
}

# wall-clock deadline via $SECONDS: counting POLL_S per iteration
# omitted the 90s probe timeout and overran MAX_WAIT_S by ~75%
SECONDS=0
while true; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    log "tunnel is up"; break
  fi
  if [ "$SECONDS" -ge "$MAX_WAIT_S" ]; then
    log "gave up waiting for tunnel after ${SECONDS}s"; exit 2
  fi
  log "tunnel down; waited ${SECONDS}s"
  sleep "$POLL_S"
done

log "=== stage 1: bench.py (insurance number, committed selections) ==="
timeout 4500 python bench.py \
  >"logs/bench_${RTAG}_stage1.log" 2>"logs/bench_${RTAG}_stage1.err"
log "bench rc=$?; $(tail -1 "logs/bench_${RTAG}_stage1.log" 2>/dev/null)"
snapshot_chip_bench
commit_evidence "${RTAG} chip: stage1 bench"

log "=== stage 2: wedge-safe profiler sections ==="
touch .queue_stage2_stamp
timeout 4800 python tools/profile_kernels.py \
  intersect ingress_ab window host_stream host_reduce host_snapshot \
  >"logs/profile_${RTAG}_safe.log" 2>"logs/profile_${RTAG}_safe.err"
log "profile-safe rc=$?"
timeout 120 python tools/update_perf_md.py \
  >>"logs/profile_${RTAG}_safe.log" 2>&1
commit_evidence "${RTAG} chip: safe profiler sections (ingress A/B, sweeps, host tiers)"

if fresh_chip_rows .queue_stage2_stamp; then
  log "=== stage 3: bench.py (chip-tuned K / ingress / chunk) ==="
  timeout 4500 python bench.py \
    >"logs/bench_${RTAG}_stage3.log" 2>"logs/bench_${RTAG}_stage3.err"
  log "bench2 rc=$?; $(tail -1 "logs/bench_${RTAG}_stage3.log" 2>/dev/null)"
  snapshot_chip_bench
  commit_evidence "${RTAG} chip: stage3 tuned bench"
else
  log "stage 3 skipped: stage 2 landed no fresh chip rows"
fi

log "=== stage 4: compile probes + wedge-prone sections (LAST) ==="
touch .queue_stage4_stamp
timeout 9000 python tools/profile_kernels.py \
  compile_probe compile_probe_scan chunk_deep dense roofline trace \
  fused driver \
  >"logs/profile_${RTAG}_deep.log" 2>"logs/profile_${RTAG}_deep.err"
log "profile-deep rc=$?"
timeout 120 python tools/update_perf_md.py \
  >>"logs/profile_${RTAG}_deep.log" 2>&1
commit_evidence "${RTAG} chip: probes + deep sections (caps, MFU, chunk_deep)"

# Gate on THIS stage's log, not PERF.json: the merged file retains a
# prior run's chunk_deep rows even when this stage's section failed
# (flush() records chunk_deep_error alongside). The orchestrator
# prints {"chunk_deep": [...]} only on a fresh success.
if fresh_chip_rows .queue_stage4_stamp \
    && grep -q '"chunk_deep": \[' "logs/profile_${RTAG}_deep.log"; then
  log "=== stage 5: bench.py (re-reads raised caps / deep chunks) ==="
  timeout 4500 python bench.py \
    >"logs/bench_${RTAG}_stage5.log" 2>"logs/bench_${RTAG}_stage5.err"
  log "bench3 rc=$?; $(tail -1 "logs/bench_${RTAG}_stage5.log" 2>/dev/null)"
  snapshot_chip_bench
  commit_evidence "${RTAG} chip: stage5 deep-chunk bench"
else
  log "stage 5 skipped: no fresh chunk_deep rows landed"
fi

log "=== stage 6: scale_run (chip legs) ==="
timeout 7200 python tools/scale_run.py \
  >"logs/scale_${RTAG}.log" 2>"logs/scale_${RTAG}.err"
log "scale rc=$?"
commit_evidence "${RTAG} chip: scale ladder"

log "=== stage 7: sharded collectives section (CPU mesh; chip-free) ==="
timeout 3600 python tools/profile_kernels.py sharded \
  >"logs/profile_${RTAG}_sharded.log" 2>"logs/profile_${RTAG}_sharded.err"
log "sharded rc=$?"
commit_evidence "${RTAG}: sharded collectives refresh (CPU mesh)"
log "queue done"

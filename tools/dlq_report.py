#!/usr/bin/env python
"""Dead-letter journal triage: render what the admission sanitizer
rejected, and re-inject it after an operator fix.

The sanitizer (utils/sanitize.py, GS_SANITIZE) peels structurally
invalid records off every admission boundary into a CRC-framed
segment journal under GS_DLQ_DIR — origin tenant, absolute source
offsets, typed reason code, and the rejected edges themselves. This
tool is the operator's other half of that contract:

  render      per tenant × reason counts, segment inventory, sample
              rows — "what is my hostile client actually sending?"
  --export    dump one tenant's (or everyone's) rejected edges as
              'src dst' lines for offline analysis
  --reinject  feed the rejected records back through a live serving
              front-end (core/serve wire protocol) in ORIGINAL source
              order — per tenant, records are merged by their recorded
              source offsets, so re-injection is replay-exact: the
              edges arrive in exactly the order they were first fed.
              Combine with --fix once the root cause is addressed
              (e.g. `--fix mod:<vb>` maps out-of-range ids into the
              bucket after a wrong-bucket deploy).

Usage:
  python tools/dlq_report.py DIR [--json] [--tenant T]
  python tools/dlq_report.py DIR --export edges.txt [--tenant T]
  python tools/dlq_report.py DIR --reinject PORT [--fix mod:VB]

Exit 0 on success (render mode exits 0 even on an empty journal —
empty is the healthy state); 1 on re-injection failures.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from gelly_streaming_tpu.utils import sanitize  # noqa: E402


def gather(directory: str, tenant=None):
    """{tenant: (offsets, src, dst, reasons)} merged across records
    and sorted by source offset — the original feed order."""
    per = {}
    for rec in sanitize.replay(directory):
        if tenant is not None and rec["tenant"] != str(tenant):
            continue
        slot = per.setdefault(rec["tenant"], [[], [], [], []])
        slot[0].append(rec["offsets"])
        slot[1].append(rec["src"])
        slot[2].append(rec["dst"])
        slot[3].extend([rec["reason"]] * len(rec["src"]))
    out = {}
    for tid, (offs, srcs, dsts, reasons) in per.items():
        o = np.concatenate(offs) if offs else np.zeros(0, np.int64)
        s = np.concatenate(srcs) if srcs else np.zeros(0, np.int64)
        d = np.concatenate(dsts) if dsts else np.zeros(0, np.int64)
        r = np.array(reasons, object)
        order = np.argsort(o, kind="stable")
        out[tid] = (o[order], s[order], d[order], r[order])
    return out


def make_fix(spec):
    """An edge transform from a --fix spec: `mod:VB` maps both ids
    into [0, VB) (the wrong-bucket deploy repair); None = identity."""
    if spec is None:
        return None
    kind, _, arg = spec.partition(":")
    if kind == "mod":
        vb = int(arg)

        def fix(src, dst):
            return np.mod(src, vb), np.mod(dst, vb)

        return fix
    raise ValueError("unknown --fix spec %r (supported: mod:VB)" % spec)


def reinject(directory: str, feed, tenant=None, fix=None,
             batch: int = 4096) -> dict:
    """Feed every journaled record back through `feed(tenant, src,
    dst)` (any callable with the cohort-feed signature) in original
    source order, `fix`-transformed when given. Returns per-tenant
    re-injected edge counts. The caller owns backpressure retries —
    a feed() that raises aborts with the exception."""
    counts = {}
    for tid, (offs, src, dst, _r) in sorted(
            gather(directory, tenant).items()):
        if fix is not None:
            src, dst = fix(src, dst)
        for lo in range(0, len(src), batch):
            feed(tid, src[lo:lo + batch], dst[lo:lo + batch])
        counts[tid] = int(len(src))
    return counts


def render(directory: str, tenant=None, as_json=False,
           samples: int = 3) -> str:
    info = sanitize.scan(directory)
    if as_json:
        return json.dumps(info, indent=2, sort_keys=True)
    lines = ["dead-letter journal %s" % directory,
             "  records: %d   edges: %d   segments: %d"
             % (info["records"], info["edges"], info["segments"])]
    if not info["records"]:
        lines.append("  (empty — the healthy state)")
        return "\n".join(lines)
    lines.append("  by reason: " + "  ".join(
        "%s=%d" % kv for kv in sorted(info["by_reason"].items())))
    for tid, (offs, src, dst, reasons) in sorted(
            gather(directory, tenant).items()):
        lines.append("  tenant %r: %d rejected edge(s)"
                     % (tid, len(src)))
        for i in range(min(samples, len(src))):
            lines.append("    offset %d: (%d, %d) — %s"
                         % (offs[i], src[i], dst[i], reasons[i]))
        if len(src) > samples:
            lines.append("    ... %d more" % (len(src) - samples))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("dir", help="dead-letter journal directory "
                               "(GS_DLQ_DIR)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable scan summary")
    ap.add_argument("--tenant", default=None,
                    help="restrict to one origin tenant")
    ap.add_argument("--export", default=None, metavar="PATH",
                    help="write rejected edges as 'src dst' lines")
    ap.add_argument("--reinject", type=int, default=None,
                    metavar="PORT",
                    help="feed records back through a live serve "
                         "front-end on 127.0.0.1:PORT")
    ap.add_argument("--fix", default=None,
                    help="edge transform before re-injection "
                         "(`mod:VB`)")
    args = ap.parse_args(argv)

    if args.export:
        per = gather(args.dir, args.tenant)
        n = 0
        with open(args.export, "w") as f:
            for tid, (_o, src, dst, _r) in sorted(per.items()):
                for s, d in zip(src.tolist(), dst.tolist()):
                    f.write("%d %d\n" % (s, d))
                    n += 1
        print("exported %d edge(s) to %s" % (n, args.export))
        return 0

    if args.reinject is not None:
        from gelly_streaming_tpu.core.serve import ServeClient

        fix = make_fix(args.fix)
        cli = ServeClient(args.reinject)
        try:
            def feed(tid, src, dst):
                r = cli.request(op="feed", tenant=tid,
                                src=np.asarray(src).tolist(),
                                dst=np.asarray(dst).tolist())
                if not r.get("ok"):
                    raise RuntimeError(
                        "re-injection refused for tenant %r: %s"
                        % (tid, r))

            counts = reinject(args.dir, feed, tenant=args.tenant,
                              fix=fix)
        except (RuntimeError, OSError) as e:
            print("dlq_report: re-injection failed: %s" % e,
                  file=sys.stderr)
            return 1
        finally:
            cli.close()
        print("re-injected: %s" % json.dumps(counts, sort_keys=True))
        return 0

    print(render(args.dir, tenant=args.tenant, as_json=args.json))
    return 0


if __name__ == "__main__":
    sys.exit(main())

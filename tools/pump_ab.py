#!/usr/bin/env python
"""Async-pump + pane-composition A/B (ISSUE 18): two probes, each a
JSON row merged BY PROBE into the committed `pump_ab` evidence.

  serving_pump — the serving overlap claim: N tenants fed window by
              window through the loopback wire protocol with the
              latency plane armed, GS_PUMP=async (dedicated dispatch
              thread; ingest returns as soon as the edges are
              sanitized + journaled + queued) vs GS_PUMP=sync (the
              single-lock legacy path, the client pumping each
              round). Per-tenant sha256 over the summary streams must
              match EXACTLY across modes before any improvement is
              claimed; the row carries serving `queue_wait` p99 and
              e2e p99 per mode (lower is better — bench_compare's
              *_p99_s latency identity) plus wall dispersion.
  sliding_panes — the refold-elimination claim: WindowedEdgeReduce
              slide= (fold each edge into its pane ONCE, compose
              panes_per_window pane summaries per emission) vs the
              naive refold twin (process_stream_naive: every emission
              refolds its whole trailing window). Integer values so
              bit-exact parity is well-defined under pane
              reassociation; panes_per_window >= 4 per the acceptance
              bar.

Timing is median-of-3 with min/max dispersion in the row. The
acceptance bars (queue_wait/e2e p99 >= 1.2x at N=8; pane path
>= 1.5x at wp >= 4) are REPORTED, not enforced: a miss is committed
honestly and the async pump stays opt-in, like the resident tier.

`--smoke` defers to tools/pump_smoke.py (the ci_check gate).
"""

import hashlib
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from bench import make_stream  # noqa: E402
from tools.egress_ab import _dispersion, timed_stats  # noqa: E402
from tools.tenancy_ab import (  # noqa: E402
    digest_summaries, make_tenant_streams, scoped_env)


# ----------------------------------------------------------------------
# serving_pump
# ----------------------------------------------------------------------
def _feed_with_retry(cli, tid, s, d):
    """Ride the protocol's typed backpressure hint — the pump compiles
    on its first dispatch, so early feeds can fill the bounded queue."""
    deadline = time.monotonic() + 120
    while True:
        r = cli.feed(tid, s, d)
        if r.get("ok"):
            return
        if r.get("error") != "TenantBackpressure" \
                or time.monotonic() > deadline:
            raise RuntimeError("feed refused: %s" % r)
        time.sleep(r.get("retry_after_s", 0.05))


def serve_once(streams, eb, vb, mode: str,
               arrival_sleep_s: float = 0.05):
    """One serving run through the loopback wire protocol under
    GS_PUMP=`mode` with the latency plane armed. Arrivals are PACED
    identically in both modes (one window per tenant per round,
    `arrival_sleep_s` between feeds) so the lever under test is
    dispatch overlap, not arrival rate — an unthrottled client is a
    batch loader, and batch loading buries the pump's latency story
    under self-inflicted backlog (the pacing must also keep arrivals
    inside the dispatch-rate envelope, or BOTH modes just measure
    saturation). Returns (wall_s, per-tenant summaries, queue_wait
    p99, worst per-tenant e2e p99)."""
    from gelly_streaming_tpu.core.serve import ServeClient, StreamServer
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.utils import latency

    with scoped_env(GS_PUMP=mode, GS_LATENCY="1"):
        latency.reset()
        cohort = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        srv = StreamServer(cohort, port=0).start()
        try:
            cli = ServeClient(srv.port, timeout=120)
            for tid in streams:
                cli.admit(tid)
            cursors = {tid: 0 for tid in streams}
            # warmup round: compile the cohort's dispatch programs
            # OUTSIDE the measured phase — a fresh cohort's first
            # dispatch JIT-compiles for seconds, which would dominate
            # both modes' p99 (inline under sync, as queue backlog
            # under async) and bury the steady-state serving story
            for tid, (s, d) in streams.items():
                _feed_with_retry(cli, tid, s[:eb], d[:eb])
                cursors[tid] = min(eb, len(s))
            if mode == "sync":
                cli.pump()
            else:
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline and any(
                        not srv.results.get(t) for t in streams):
                    time.sleep(0.02)
            latency.reset()  # steady-state percentiles only
            t0 = time.perf_counter()
            live = True
            while live:
                live = False
                for tid, (s, d) in streams.items():
                    c = cursors[tid]
                    if c >= len(s):
                        continue
                    hi = min(c + eb, len(s))
                    _feed_with_retry(cli, tid, s[c:hi], d[c:hi])
                    time.sleep(arrival_sleep_s)
                    cursors[tid] = hi
                    live = True
                if mode == "sync":
                    # legacy serving: the caller's round-boundary pump
                    # IS the dispatch — a window fed early in the
                    # round waits for it; async dispatches as soon as
                    # a window completes, overlapped with the rest of
                    # the round's ingest
                    cli.pump()
            cli.close()
            srv.drain(deadline_s=120)
            wall = time.perf_counter() - t0
            sec = latency.health_section()
            qw = sec["stages"].get("queue_wait", {}).get("p99_s")
            e2e = max((row["e2e_p99_s"]
                       for row in sec["tenants"].values()),
                      default=None)
            out = {tid: [row["summary"] for row in rows]
                   for tid, rows in srv.results.items()}
            return wall, out, qw, e2e
        finally:
            srv.close()
            latency.reset()


def probe_serving_pump(jax, streams, eb, vb, results: list) -> None:
    reps = {}
    for mode in ("sync", "async"):
        runs = [serve_once(streams, eb, vb, mode) for _ in range(3)]
        runs.sort(key=lambda r: r[0])
        walls = [r[0] for r in runs]
        # the median-wall rep's latency percentiles ride the row (one
        # rep = one armed plane; averaging percentiles across planes
        # would manufacture numbers no run observed)
        reps[mode] = {
            "stats": (walls[1], walls[0], walls[2]),
            "out": runs[1][1],
            "queue_wait_p99_s": runs[1][2],
            "e2e_p99_s": runs[1][3],
        }
    sync, asyn = reps["sync"], reps["async"]
    digests = {t: digest_summaries(sync["out"][t])
               for t in sorted(streams)}
    parity = all(digest_summaries(asyn["out"].get(t, []))
                 == digests[t] for t in streams)
    row = {
        "probe": "serving_pump",
        "backend": jax.default_backend(),
        "tenants": len(streams),
        "eb": eb, "vb": vb,
        "num_edges": sum(len(s) for s, _d in streams.values()),
        "parity": bool(parity),
        "tenant_digests": digests,
        "sync_queue_wait_p99_s": sync["queue_wait_p99_s"],
        "async_queue_wait_p99_s": asyn["queue_wait_p99_s"],
        "sync_e2e_p99_s": sync["e2e_p99_s"],
        "async_e2e_p99_s": asyn["e2e_p99_s"],
    }
    _dispersion(row, "sync", sync["stats"])
    _dispersion(row, "async", asyn["stats"])
    if not parity:
        bad = [t for t in streams
               if digest_summaries(asyn["out"].get(t, []))
               != digests[t]]
        print("PARITY FAILURE (serving_pump): tenants %s diverged "
              "across pump modes" % bad, file=sys.stderr)
    else:
        if sync["queue_wait_p99_s"] and asyn["queue_wait_p99_s"]:
            row["queue_wait_improvement"] = round(
                sync["queue_wait_p99_s"] / asyn["queue_wait_p99_s"],
                3)
        if sync["e2e_p99_s"] and asyn["e2e_p99_s"]:
            row["e2e_improvement"] = round(
                sync["e2e_p99_s"] / asyn["e2e_p99_s"], 3)
        # headline ratio: the serving e2e tail — what a caller feels
        row["speedup"] = row.get("e2e_improvement") or round(
            sync["stats"][0] / asyn["stats"][0], 3)
    results.append(row)
    print(json.dumps(row), flush=True)


# ----------------------------------------------------------------------
# sliding_panes
# ----------------------------------------------------------------------
def _digest_reduce(windows) -> str:
    h = hashlib.sha256()
    for cells, counts in windows:
        h.update(np.ascontiguousarray(cells).tobytes())
        h.update(np.ascontiguousarray(counts).tobytes())
    return h.hexdigest()[:16]


def probe_sliding_panes(jax, eb, vb, slide, windows, results: list,
                        name: str = "sum",
                        direction: str = "out") -> None:
    from gelly_streaming_tpu.ops.windowed_reduce import (
        WindowedEdgeReduce)

    n = windows * eb + slide // 2  # ragged tail exercises the close
    s, d = make_stream(n, vb, seed=23)
    s, d = s.astype(np.int32), d.astype(np.int32)
    # integer values: float pane sums reassociate and are not
    # bit-stable, so the parity identity would be vacuous
    val = np.random.default_rng(24).integers(
        -1000, 1000, n).astype(np.int64)

    pane_eng = WindowedEdgeReduce(vb, eb, name=name,
                                  direction=direction, slide=slide)
    naive_eng = WindowedEdgeReduce(vb, eb, name=name,
                                   direction=direction, slide=slide)
    got = pane_eng.process_stream(s, d, val)
    want = naive_eng.process_stream_naive(s, d, val)
    parity = len(got) == len(want) and all(
        np.array_equal(gc, nc) and np.array_equal(gn, nn)
        for (gc, gn), (nc, nn) in zip(got, want))

    pane = timed_stats(
        lambda: pane_eng.process_stream(s, d, val), reps=3, warmup=1)
    naive = timed_stats(
        lambda: naive_eng.process_stream_naive(s, d, val),
        reps=3, warmup=1)
    row = {
        "probe": "sliding_panes",
        "backend": jax.default_backend(),
        "eb": eb, "vb": vb, "slide": slide,
        "panes_per_window": eb // slide,
        "monoid": name, "direction": direction,
        "num_edges": n,
        "emissions": -(-n // slide),
        "parity": bool(parity),
        "digest": _digest_reduce(got),
        "pane_edges_per_s": round(n / pane[0]),
        "naive_edges_per_s": round(n / naive[0]),
    }
    _dispersion(row, "pane", pane)
    _dispersion(row, "naive", naive)
    if parity:
        row["speedup"] = round(naive[0] / pane[0], 3)
        row["speedup_worst"] = round(naive[1] / pane[2], 3)
        row["speedup_best"] = round(naive[2] / pane[1], 3)
    else:
        print("PARITY FAILURE (sliding_panes): pane path diverged "
              "from the naive refold twin", file=sys.stderr)
    results.append(row)
    print(json.dumps(row), flush=True)


PROBE_NAMES = ("serving_pump", "sliding_panes")


def commit_results(results, backend: str) -> None:
    """Merge BY PROBE into PERF.json (backend-matched) and the
    per-backend archive — the tools/tenancy_ab.py policy."""
    ran = {r["probe"] for r in results}
    targets = ((os.path.join(REPO, "PERF.json"), True),
               (os.path.join(REPO, "PERF_%s.json" % backend), False))
    for path, need_match in targets:
        try:
            with open(path) as f:
                cur = json.load(f)
        except (OSError, ValueError):
            cur = {}
        if need_match and cur.get("backend") != backend:
            print("not committing to %s: file backend %r != live %r"
                  % (os.path.basename(path), cur.get("backend"),
                     backend), file=sys.stderr)
            continue
        cur.setdefault("backend", backend)
        kept = [r for r in cur.get("pump_ab", [])
                if r.get("probe") not in ran]
        cur["pump_ab"] = kept + results
        with open(path, "w") as f:
            json.dump(cur, f, indent=2)
        print("committed %s row(s) to %s (%d prior row(s) kept)"
              % (len(results), os.path.basename(path), len(kept)),
              flush=True)


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("probes", nargs="*",
                    help="subset of %s (default: all)" % (PROBE_NAMES,))
    ap.add_argument("--tenants", type=int,
                    default=int(os.environ.get("GS_AB_TENANTS", 8)))
    ap.add_argument("--windows", type=int,
                    default=int(os.environ.get("GS_AB_WINDOWS", 6)),
                    help="windows per tenant (serving probe)")
    ap.add_argument("--eb", type=int,
                    default=int(os.environ.get("GS_AB_EB", 512)))
    ap.add_argument("--vb", type=int,
                    default=int(os.environ.get("GS_AB_VB", 1024)))
    ap.add_argument("--slide", type=int,
                    default=int(os.environ.get("GS_AB_SLIDE", 128)),
                    help="pane size (sliding probe; eb/slide = "
                         "panes_per_window)")
    ap.add_argument("--sliding-windows", type=int, default=40,
                    help="full windows of edges in the sliding probe")
    ap.add_argument("--smoke", action="store_true",
                    help="defer to tools/pump_smoke.py (ci gate)")
    ap.add_argument("--commit", action="store_true")
    args = ap.parse_args()
    bad = [p for p in args.probes if p not in PROBE_NAMES]
    if bad:
        ap.error("unknown probe(s) %s; valid: %s"
                 % (bad, list(PROBE_NAMES)))
    want = args.probes or list(PROBE_NAMES)

    if args.smoke:
        from tools import pump_smoke
        sys.exit(pump_smoke.main())

    os.environ["GS_AUTOTUNE"] = "0"
    import jax

    results = []
    if "serving_pump" in want:
        streams = make_tenant_streams(args.tenants, args.windows,
                                      args.eb, args.vb)
        probe_serving_pump(jax, streams, args.eb, args.vb, results)
    if "sliding_panes" in want:
        probe_sliding_panes(jax, args.eb, args.vb, args.slide,
                            args.sliding_windows, results)
    out = os.path.join(REPO, "logs",
                       "pump_ab_%s.json" % jax.default_backend())
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote %s" % out, flush=True)
    if args.commit:
        commit_results(results, jax.default_backend())


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Read a flight-recorder run ledger (utils/telemetry JSONL) and turn
it into operator-readable evidence:

  - per-span latency histograms (count, total, p50/p95/p99) using the
    recorder's own nearest-rank percentile math,
  - per-window/per-chunk throughput (edges/s from spans that carry an
    `edges` attribute),
  - the event timeline (faults, retries, demotions, checkpoints,
    resumes, autotune decisions) in wall-clock order,
  - a Chrome/Perfetto `trace.json` export (`--perfetto out.json`) for
    flame-style inspection: load it at ui.perfetto.dev or
    chrome://tracing.

Ledger damage tolerance matches the writer's contract: a torn final
line (the process died mid-append) is skipped, not fatal — the whole
point of a crash-safe recorder is that its reader works on the ledger
a crash left behind.

Usage:
  python tools/trace_report.py LEDGER.jsonl [--perfetto out.json]
                               [--json] [--top N]
                               [--trace-id ID] [--since SECONDS]

An empty / torn-only ledger — or filters that match nothing — exits
non-zero with a message naming the problem, never an empty percentile
table (an unattended chip-window script must fail loudly there).
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from gelly_streaming_tpu.utils.telemetry import percentiles  # noqa: E402


def load(path: str) -> list:
    """Parse one ledger: a list of record dicts, bad/torn lines
    skipped. Raises on an unreadable FILE (that is operational, not
    damage)."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line: the crash the ledger is for
            if isinstance(rec, dict) and "t" in rec:
                records.append(rec)
    return records


def filter_records(records: list, trace_id: str = None,
                   since: float = None) -> list:
    """Narrow a ledger: `trace_id` keeps one run's records (ledgers
    under a reused GS_TRACE_DIR accumulate several; meta lines follow
    their trace), `since` keeps records whose monotonic `ts` is at or
    past that many seconds (meta lines are kept — they anchor the
    clock mapping)."""
    out = records
    if trace_id is not None:
        out = [r for r in out if r.get("trace") == trace_id]
    if since is not None:
        out = [r for r in out
               if r["t"] == "meta" or float(r.get("ts", 0.0)) >= since]
    return out


def meta_of(records: list) -> dict:
    for rec in records:
        if rec["t"] == "meta":
            return rec
    return {}


def span_rows(records: list, cost: dict = None) -> list:
    """Per-span-name latency rows, sorted by total time — the same
    shape telemetry.summary() commits to PERF.json. Spans tagged by
    the cost observatory (program/sig attributes) group per program
    signature and, when a cost index (`cost_index`) is given, carry
    that program's FLOPs/bytes beside the latencies."""
    groups = {}
    for rec in records:
        if rec["t"] != "span":
            continue
        a = rec.get("a") or {}
        key = (rec["name"], a.get("program"), a.get("sig"))
        groups.setdefault(key, []).append(float(rec.get("dur", 0.0)))
    rows = []
    for (name, program, sig), durs in groups.items():
        pct = percentiles(durs)
        row = {
            "span": name,
            "count": len(durs),
            "total_ms": round(sum(durs) * 1e3, 3),
            "p50_ms": round(pct[50] * 1e3, 3),
            "p95_ms": round(pct[95] * 1e3, 3),
            "p99_ms": round(pct[99] * 1e3, 3),
        }
        if program:
            row["program"] = program
            row["sig"] = sig
            centry = (cost or {}).get((program, sig)) \
                or (cost or {}).get((program, None))
            if centry:
                row["flops"] = centry.get("flops")
                row["bytes_accessed"] = centry.get("bytes_accessed")
                row["bound"] = centry.get("bound")
        rows.append(row)
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def cost_index(perf: dict) -> dict:
    """{(program, sig) → cost row} from a PERF document's cost_model
    section (plus a (program, None) fallback per program), so span
    tables and Perfetto exports can carry FLOPs/bytes metadata."""
    out = {}
    for row in ((perf or {}).get("cost_model") or {}).get(
            "programs") or []:
        if not isinstance(row, dict):
            continue
        out[(row.get("program"), row.get("sig"))] = row
        out.setdefault((row.get("program"), None), row)
    return out


def throughput_rows(records: list) -> list:
    """edges/s per span name, from spans carrying an `edges`
    attribute (the engine rounds and chunk spans do)."""
    groups = {}
    for rec in records:
        if rec["t"] != "span":
            continue
        edges = (rec.get("a") or {}).get("edges")
        if not edges:
            continue
        g = groups.setdefault(rec["name"], {"edges": 0, "s": 0.0,
                                            "n": 0})
        g["edges"] += int(edges)
        g["s"] += float(rec.get("dur", 0.0))
        g["n"] += 1
    return [{"span": name, "rounds": g["n"], "edges": g["edges"],
             "edges_per_s": round(g["edges"] / g["s"]) if g["s"] else 0}
            for name, g in sorted(groups.items())]


def event_rows(records: list) -> list:
    out = [rec for rec in records if rec["t"] == "event"]
    out.sort(key=lambda rec: rec.get("ts", 0.0))
    return out


def to_perfetto(records: list, cost: dict = None) -> dict:
    """Chrome trace-event JSON (the object form with `traceEvents`):
    one complete ('X') event per span with microsecond ts/dur, one
    instant ('i') event per recorded event, counters as 'C'. Span
    timestamps are the recorder's monotonic clock; the meta line's
    epoch/mono anchor is attached as trace metadata. With a cost
    index (`cost_index`), program-tagged spans carry their FLOPs/
    bytes/boundedness in the event args, so the exported flame view
    explains each slice's cost model inline."""
    meta = meta_of(records)
    pid = meta.get("pid", 0)
    events = []
    for rec in records:
        kind = rec["t"]
        if kind == "meta":
            continue
        base = {
            "name": rec.get("name", "?"),
            "pid": pid,
            "tid": rec.get("tid", 0),
            "ts": round(float(rec.get("ts", 0.0)) * 1e6, 3),
        }
        args = dict(rec.get("a") or {})
        if args.get("program") and cost:
            centry = cost.get((args["program"], args.get("sig"))) \
                or cost.get((args["program"], None))
            if centry:
                for k in ("flops", "bytes_accessed", "bound"):
                    if centry.get(k) is not None:
                        args[k] = centry[k]
        if kind == "span":
            events.append(dict(
                base, ph="X", cat="span",
                dur=round(float(rec.get("dur", 0.0)) * 1e6, 3),
                args=dict(args, sid=rec.get("sid"),
                          par=rec.get("par"))))
        elif kind == "event":
            events.append(dict(base, ph="i", cat="event", s="p",
                               args=args))
        elif kind in ("counter", "gauge"):
            events.append(dict(base, ph="C", cat=kind,
                               args={"value": rec.get("value", 0)}))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"trace": meta.get("trace"),
                      "epoch": meta.get("epoch"),
                      "mono": meta.get("mono")},
    }


def render(records: list, top: int = 0, cost: dict = None) -> str:
    meta = meta_of(records)
    lines = ["ledger trace=%s pid=%s  (%d records)"
             % (meta.get("trace", "?"), meta.get("pid", "?"),
                len(records)), ""]
    rows = span_rows(records, cost)
    if top:
        rows = rows[:top]
    if rows:
        lines += ["span                        count   total ms"
                  "    p50 ms    p95 ms    p99 ms  program",
                  "-" * 78]
        for r in rows:
            prog = r.get("program") or ""
            if prog and r.get("flops"):
                prog += "  [%.2fGF/%.0fMB %s]" % (
                    r["flops"] / 1e9,
                    (r.get("bytes_accessed") or 0) / 1e6,
                    r.get("bound", "?"))
            lines.append(
                "%-27s %5d %10.3f %9.3f %9.3f %9.3f  %s"
                % (r["span"][:27], r["count"], r["total_ms"],
                   r["p50_ms"], r["p95_ms"], r["p99_ms"], prog))
        lines.append("")
    thr = throughput_rows(records)
    if thr:
        lines += ["throughput (spans carrying `edges`):"]
        for r in thr:
            lines.append("  %-27s %5d rounds  %10d edges  %10d edges/s"
                         % (r["span"][:27], r["rounds"], r["edges"],
                            r["edges_per_s"]))
        lines.append("")
    evs = event_rows(records)
    if evs:
        lines += ["event timeline:"]
        for rec in evs:
            attrs = " ".join("%s=%s" % kv
                             for kv in sorted((rec.get("a")
                                               or {}).items()))
            lines.append("  %12.6fs  %-20s %s"
                         % (float(rec.get("ts", 0.0)),
                            rec.get("name", "?"), attrs))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("ledger", help="run ledger (trace_*.jsonl)")
    ap.add_argument("--perfetto", metavar="OUT",
                    help="write a Chrome/Perfetto trace.json here")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as JSON instead of text")
    ap.add_argument("--top", type=int, default=0,
                    help="limit the span table to the top N rows")
    ap.add_argument("--trace-id", default=None,
                    help="keep only records of this run trace ID")
    ap.add_argument("--since", type=float, default=None,
                    help="keep only records with monotonic ts >= this "
                         "many seconds")
    ap.add_argument("--perf", default=None,
                    help="PERF*.json whose cost_model section "
                         "annotates program-tagged spans with "
                         "FLOPs/bytes (table + Perfetto args)")
    args = ap.parse_args(argv)

    cost = None
    if args.perf:
        try:
            with open(args.perf) as f:
                cost = cost_index(json.load(f))
        except (OSError, ValueError) as e:
            print("trace_report: unreadable --perf %s (%s)"
                  % (args.perf, e), file=sys.stderr)
            return 1

    records = load(args.ledger)
    if not records:
        print("trace_report: no usable records in %s — the ledger is "
              "empty or holds only torn lines (did the run arm "
              "GS_TELEMETRY=1 and flush?)" % args.ledger,
              file=sys.stderr)
        return 1
    records = filter_records(records, args.trace_id, args.since)
    body = [r for r in records if r["t"] != "meta"]
    if not body:
        parts = []
        if args.trace_id is not None:
            parts.append("--trace-id %s" % args.trace_id)
        if args.since is not None:
            parts.append("--since %g" % args.since)
        print("trace_report: no records%s in %s — nothing to report"
              % ((" matching " + " ".join(parts)) if parts
                 else " besides the meta anchor", args.ledger),
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps({
            "meta": meta_of(records),
            "spans": span_rows(records, cost)[:args.top or None],
            "throughput": throughput_rows(records),
            "events": event_rows(records),
        }, indent=2, default=str))
    else:
        print(render(records, args.top, cost))
    if args.perfetto:
        trace = to_perfetto(records, cost)
        with open(args.perfetto, "w") as f:
            json.dump(trace, f)
        print("wrote %s (%d trace events)"
              % (args.perfetto, len(trace["traceEvents"])),
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

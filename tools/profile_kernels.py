#!/usr/bin/env python
"""On-chip perf characterization of the hot kernels (VERDICT r1 items
2-3): times each ⚡ path of SURVEY.md §2 on the active backend, derives
bytes-moved / FLOP / MFU-roofline estimates, and emits one JSON object
per section plus a combined PERF.json.

Sections (argv selects a subset; default: all single-chip):
  intersect  — chunked broadcast-compare vs per-row binary search
               (pins the 438ms->6.8ms claim in ops/triangles.py:94-99)
  window     — TriangleWindowKernel.count_stream per-window ms + MB/s
               (reference hot path: WindowTriangles.java:61-66)
  fused      — StreamSummaryEngine.process per-window ms (all four
               analytics fused; WindowGraphAggregation.java:54-58)
  dense      — XLA dense matmul vs Pallas fused contraction at
               V = 1024/2048/4096 (drives the dense-path auto-select)
  sharded    — sharded engines on the virtual 8-device CPU mesh
               (run in a subprocess so the backend pin doesn't leak)

Peak numbers for MFU/roofline are the public TPU v5e (v5 lite) specs:
197 TFLOP/s bf16 (MXU; f32 inputs run below this), 819 GB/s HBM.
Results on a CPU backend are labeled as such and never masquerade as
chip numbers (same contract as bench.py).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np

PEAK_BF16_TFLOPS = 197.0   # TPU v5e MXU peak (public spec)
PEAK_HBM_GBPS = 819.0      # TPU v5e HBM bandwidth (public spec)


def _timeit(fn, reps: int = 5, warmup: int = 2) -> float:
    """Median wall seconds of fn() over reps after warmup calls. fn must
    block until the device result is ready (np.asarray / block_until_ready)."""
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _stream(num_edges: int, num_vertices: int, seed: int = 7):
    from bench import make_stream

    return make_stream(num_edges, num_vertices, seed)


def section_intersect(results: dict) -> None:
    """The dominant sparse kernel: |N(a) ∩ N(b)| per oriented edge.
    Compare the shipped chunked broadcast-equality compare against the
    vmap(searchsorted) binary-search lowering it replaced."""
    import jax
    import jax.numpy as jnp

    from gelly_streaming_tpu.ops.triangles import intersect_local

    ep, k, vb = 16_384, 256, 1 << 16
    rng = np.random.default_rng(3)
    # plausible sorted dedup'd neighbor rows: ~K/4 real entries per row
    fill = rng.integers(0, vb, size=(vb + 1, k), dtype=np.int32)
    fill.sort(axis=1)
    keep = np.arange(k) < k // 4
    nbr = np.where(keep[None, :], fill, vb).astype(np.int32)
    ea = rng.integers(0, vb, size=ep, dtype=np.int32)
    eb_ = rng.integers(0, vb, size=ep, dtype=np.int32)
    emask = np.ones(ep, bool)
    args = tuple(jnp.asarray(x) for x in (nbr, ea, eb_, emask))

    from gelly_streaming_tpu.ops.triangles import intersect_local_bsearch

    compare = jax.jit(intersect_local)
    binary_search = jax.jit(intersect_local_bsearch)

    from gelly_streaming_tpu.ops import pallas_intersect

    want = int(compare(*args))
    parity = want == int(binary_search(*args))
    t_cmp = _timeit(lambda: compare(*args).block_until_ready())
    t_bs = _timeit(lambda: binary_search(*args).block_until_ready())
    sweep = []
    if pallas_intersect._need_interpret():
        parity_pl, t_pl = None, None
    else:
        # Tile-shape sweep (VERDICT r4 item 6: one real iteration,
        # then decide). Candidates keep the [T, Ck, K] compare tensor
        # + three [T, K] input blocks under ~14MB of VMEM at K=256.
        # The best parity-true row becomes BOTH the section's headline
        # pallas_ms (what resolve_intersect_impl gates on) and the
        # shape intersect_local_pallas ships (_resolve_tile).
        for tile_e, chunk_k in ((32, 64), (32, 128), (64, 64),
                                (64, 128), (128, 64)):
            try:
                p = want == int(pallas_intersect.intersect_local_pallas(
                    *args, tile_e=tile_e, chunk_k=chunk_k))
                t = _timeit(
                    lambda: pallas_intersect.intersect_local_pallas(
                        *args, tile_e=tile_e,
                        chunk_k=chunk_k).block_until_ready())
                sweep.append({"tile_e": tile_e, "chunk_k": chunk_k,
                              "parity": p, "ms": round(t * 1e3, 3)})
            except Exception as e:   # a shape that fails to lower is
                sweep.append({"tile_e": tile_e, "chunk_k": chunk_k,
                              "error": str(e)[:160]})  # evidence too
            print(json.dumps({"intersect_sweep": sweep[-1]}),
                  flush=True)
        good = [r for r in sweep if r.get("parity") is True]
        if good:
            best = min(good, key=lambda r: r["ms"])
            parity_pl, t_pl = True, best["ms"] / 1e3
        else:
            parity_pl, t_pl = False, None
    # compare work: Ep*K*K int equality ops (+ masked sum)
    cmp_ops = ep * k * k
    results["intersect"] = {
        "ep": ep, "k": k, "parity": parity, "parity_pallas": parity_pl,
        "broadcast_compare_ms": round(t_cmp * 1e3, 3),
        "binary_search_ms": round(t_bs * 1e3, 3),
        "pallas_ms": round(t_pl * 1e3, 3) if t_pl else None,
        "pallas_sweep": sweep,
        "speedup_vs_binary_search": round(t_bs / t_cmp, 1),
        "pallas_vs_xla_compare": (round(t_cmp / t_pl, 2) if t_pl
                                  else None),
        "compare_gops_per_s": round(cmp_ops / t_cmp / 1e9, 1),
    }


def _count_overflow_recounts(kern, src, dst) -> int:
    """Run count_stream once with kern.count instrumented, returning
    how many per-window exact recounts (K-bucket overflows) the stream
    triggers; also warms every program the stream needs."""
    overflows = [0]
    orig = kern.count

    def counting(s, d, min_k=0):
        overflows[0] += 1
        return orig(s, d, min_k)

    kern.count = counting
    try:
        # the DEVICE path explicitly: on a CPU backend with committed
        # winning host_stream rows, count_stream routes to the numpy
        # tier, which would make every K/chunk sweep row time the same
        # K-independent host code (the committed-PERF feedback the
        # sweep's anchor comments guard against)
        kern._count_stream_device(src, dst)
    finally:
        kern.count = orig
    return overflows[0]


def section_window(results: dict) -> None:
    """TriangleWindowKernel.count_stream: per-window latency and h2d
    bandwidth at three window sizes (64 windows each). The K×K
    intersection compare dominates and shrinks quadratically with the
    K bucket, so each size also sweeps K below the default — a smaller
    K wins whenever the stream's max oriented out-degree stays under
    it (overflowing windows pay an exact per-window recount, counted
    here)."""
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    # 8K/32K compile in seconds on the tunnel; the 131072-edge-window
    # program stalled its remote compiler >30 min and wedged it for
    # hours (see bench.py's window cap). Extend via GS_PROFILE_BIG=1
    # only when babysitting the run. CPU backends have no such hazard
    # and the 10M-scale legs use 65536-edge windows, so sweep that size
    # too off-chip (its tuned K feeds the scale run's kernels).
    import jax

    sizes = (8_192, 32_768)
    if jax.default_backend() == "cpu":
        sizes = sizes + (65_536,)
    if os.environ.get("GS_PROFILE_BIG") == "1":
        sizes = sizes + (131_072,)
    out = []
    for eb in sizes:
        vb = 2 * eb
        num_w = 64
        src, dst = _stream(num_w * eb, vb)
        row = {"edge_bucket": eb, "windows": num_w,
               "h2d_mb_per_chunk": round(num_w * eb * 2 * 4 / 1e6, 1),
               "k_sweep": []}
        # anchor the sweep on the ANALYTIC heuristic, never the tuned
        # value a committed PERF.json may already inject into the
        # kernel default — otherwise successive profiling runs ratchet
        # K downward and can never re-explore larger values
        default_kb = min(128, 2 * int(np.sqrt(eb)))
        # the sweeps' chunk anchor: deterministic per (backend, eb) —
        # the compile-size-capped default on the tunneled chip (the
        # 64×32768-edge program wedged the remote compiler >25 min in
        # the round-4 window; ops/triangles._default_chunk), the class
        # default elsewhere. Same ratchet guard as K: committed picks
        # never set the conditions the sweep measures under.
        from gelly_streaming_tpu.ops.triangles import _default_chunk

        anchor_chunk = _default_chunk(eb)
        kernels = {}
        for kb in sorted({default_kb, default_kb // 2, default_kb // 4}):
            kern = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb,
                                        k_bucket=kb)
            kern.MAX_STREAM_WINDOWS = anchor_chunk
            kernels[kern.kb] = kern
            # one instrumented pass counts the overflow recounts an
            # undersized K pays (and warms every program it needs),
            # then the clean timing runs uninstrumented
            overflow_count = _count_overflow_recounts(kern, src, dst)
            t = _timeit(lambda: kern._count_stream_device(src, dst),
                        reps=3, warmup=0)
            row["k_sweep"].append({
                "k_bucket": kern.kb,
                "default": kern.kb == default_kb,
                "per_window_ms": round(t / num_w * 1e3, 3),
                "edges_per_s": round(num_w * eb / t),
                "overflow_recounts_per_run": overflow_count,
            })
        # chunk sweep (windows per dispatch) at the fastest clean K: on
        # the tunneled chip each dispatch costs ~0.2s, so chunk size
        # trades h2d size against dispatch amortization; on CPU it
        # should be flat (dispatch ~free) — both facts worth pinning.
        # The stream needs AT LEAST as many windows as the largest
        # chunk (128; equality suffices — cs=128 then times one full
        # dispatch, cs=64 times two), else the biggest rows silently
        # re-time the same dispatch; reuse the k_sweep's compiled
        # kernel.
        # same selection the runtime applies (_tuned_kb): the fastest
        # MEASURED row wins outright — its timing already includes its
        # own recount cost — so the chunk sweep times the K production
        # actually runs
        best_kb = min(row["k_sweep"],
                      key=lambda s: s["per_window_ms"])["k_bucket"]
        kern = kernels[best_kb]
        cnum_w = 128
        csrc, cdst = _stream(cnum_w * eb, vb, seed=8)
        row["chunk_sweep_k"] = best_kb
        row["chunk_sweep_windows"] = cnum_w
        # warms every needed program + counts recounts once
        row["chunk_sweep_overflow_recounts"] = _count_overflow_recounts(
            kern, csrc, cdst)
        row["chunk_sweep"] = []
        if jax.default_backend() == "tpu":
            # stay under the compile-size wedge line (see anchor note)
            cs_values = sorted({max(1, anchor_chunk // 4),
                                max(1, anchor_chunk // 2), anchor_chunk})
        else:
            cs_values = [32, 64, 128]
        for cs in cs_values:
            kern.MAX_STREAM_WINDOWS = cs
            kern._count_stream_device(csrc, cdst)  # warm this chunk shape
            t = _timeit(lambda: kern._count_stream_device(csrc, cdst),
                        reps=3, warmup=0)
            row["chunk_sweep"].append({
                "windows_per_dispatch": cs,
                "default": cs == anchor_chunk,
                "per_window_ms": round(t / cnum_w * 1e3, 3),
                "edges_per_s": round(cnum_w * eb / t),
            })
        # leave the kernel at the anchor chunk (the instance attr is
        # always set now — __init__ tunes it, this sweep overwrote it)
        kern.MAX_STREAM_WINDOWS = anchor_chunk
        out.append(row)
    results["window"] = out


def section_fused(results: dict) -> None:
    """StreamSummaryEngine: all four analytics (degrees, CC,
    bipartiteness, triangles) fused into one scan dispatch."""
    from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine

    out = []
    for eb in (8_192, 32_768):
        vb = 2 * eb
        num_w = 64
        src, dst = _stream(num_w * eb, vb)
        eng = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
        eng.warm_fallback()

        def run():
            eng.reset()
            eng.process(src, dst)

        t = _timeit(run, reps=3, warmup=1)
        out.append({
            "edge_bucket": eb, "windows": num_w,
            "per_window_ms": round(t / num_w * 1e3, 3),
            "edges_per_s": round(num_w * eb / t),
        })
    results["fused"] = out


def section_driver(results: dict) -> None:
    """StreamingAnalyticsDriver end-to-end: the batched fast path (one
    snapshot-scan dispatch + one triangle stack dispatch per 64-window
    chunk) vs the per-window dispatch path on the same stream — the
    dispatch-economics win this round's driver work targets."""
    from gelly_streaming_tpu import StreamingAnalyticsDriver

    eb, num_w = 8_192, 32
    vb = 2 * eb
    src, dst = _stream(num_w * eb, vb)
    out = {}
    for mode in ("batched", "per-window"):
        drv = StreamingAnalyticsDriver(window_ms=0, edge_bucket=eb,
                                       vertex_bucket=vb)

        def run():
            drv.reset()
            if mode == "batched":
                drv.run_arrays(src, dst)
            else:
                for i in range(0, len(src), eb):
                    drv.run_arrays(src[i:i + eb], dst[i:i + eb])

        t = _timeit(run, reps=3, warmup=1)
        out[mode] = {"per_window_ms": round(t / num_w * 1e3, 3),
                     "edges_per_s": round(num_w * eb / t)}
    out["speedup"] = round(
        out["per-window"]["per_window_ms"]
        / out["batched"]["per_window_ms"], 2)
    out["edge_bucket"] = eb
    out["windows"] = num_w
    results["driver"] = out


def _dense_stream(v: int):
    e = 16 * v
    rng = np.random.default_rng(5)
    src = rng.integers(0, v, size=e, dtype=np.int32)
    dst = rng.integers(0, v, size=e, dtype=np.int32)
    keep = src != dst
    return src[keep], dst[keep]


def run_dense_child(v: int, impl: str) -> None:
    """Parity-check + time ONE dense implementation at ONE V, as its
    own process: a wedged remote compile (the r04 failure mode — the
    dense section never produced a chip MFU row in four rounds) then
    costs one (V, impl) cell, not the whole section."""
    import jax
    import jax.numpy as jnp

    from gelly_streaming_tpu.ops import pallas_triangles
    from gelly_streaming_tpu.ops.triangles import (_dense_row_counts,
                                                   triangle_count_dense,
                                                   triangle_count_sparse)

    src, dst = _dense_stream(v)
    want = triangle_count_sparse(src, dst, v)
    sj, dj = jnp.asarray(src), jnp.asarray(dst)
    if impl == "xla":
        got = triangle_count_dense(src, dst, v)
        t = _timeit(
            lambda: _dense_row_counts(sj, dj, v).block_until_ready())
    else:
        if pallas_triangles._need_interpret():
            raise SystemExit("pallas needs a real TPU backend")
        got = pallas_triangles.triangle_count_dense_pallas(src, dst, v)
        t = _timeit(lambda: pallas_triangles._adjacency_six_t(
            sj, dj, v, False).block_until_ready())
    flops = 2 * v ** 3  # the A@A contraction dominates
    print(json.dumps({
        "v": v, "impl": impl, "ok": got == want,
        "ms": round(t * 1e3, 3),
        "mfu": round(flops / t / (PEAK_BF16_TFLOPS * 1e12), 4),
        "backend": jax.default_backend(),
    }), flush=True)


def section_dense(results: dict) -> None:
    """Dense triangle path: XLA matmul (A@A ⊙ A row sums) vs the
    Pallas fused contraction, each (V, impl) compiled+timed in its own
    hard-timeout subprocess, V ASCENDING from a sub-wedge 512 — so the
    first MFU rows land even if a larger shape wedges the remote
    compiler (VERDICT r4 item 3: MFU had never been computed on chip
    because the monolithic section wedged). The winner becomes the
    default dense path — see ops/triangles.triangle_count."""
    import jax

    from bench import run_json_child

    from gelly_streaming_tpu.ops import pallas_triangles

    if pallas_triangles._need_interpret():
        # interpreter-mode Pallas timings are meaningless (and V=4096
        # takes hours on CPU); parity is already covered by tests
        results["dense"] = {"skipped": "non-TPU backend (interpret "
                                       "mode times nothing real)"}
        return
    backend = jax.default_backend()
    out = []
    for v in (512, 1024, 2048, 4096):
        row = {"v": v, "edges": int(len(_dense_stream(v)[0]))}
        for impl in ("xla", "pallas"):
            got = run_json_child(
                [sys.executable, os.path.abspath(__file__),
                 "--dense", str(v), impl], PROBE_TIMEOUT_S)
            if got.get("ok") and got.get("backend") == backend:
                row["%s_ms" % impl] = got["ms"]
                row["%s_mfu_vs_bf16_peak" % impl] = got["mfu"]
            elif got.get("ok") is False:
                row["%s_error" % impl] = "parity failure"
            else:
                row["%s_error" % impl] = str(
                    got.get("error") or "backend mismatch")[:200]
        if "xla_ms" in row and "pallas_ms" in row:
            row["pallas_speedup"] = round(
                row["xla_ms"] / row["pallas_ms"], 2)
        out.append(row)
        print(json.dumps({"dense_progress": row}), flush=True)
    results["dense"] = out


def _cost_rows(compiled):
    """(flops, bytes_accessed) from XLA's cost model for an AOT-compiled
    executable; (None, None) when the backend doesn't report them.
    Unwraps costmodel.wrap_exec wrappers (the kernels' cached stream
    executables carry the raw executable on __wrapped__)."""
    compiled = getattr(compiled, "__wrapped__", compiled)
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return ca.get("flops"), ca.get("bytes accessed")
    except Exception:
        return None, None


def _roofline_row(name, compiled, args, extra=None):
    """Time one AOT executable + place it on the v5e roofline: achieved
    GFLOP/s vs the 197 TFLOP/s bf16 MXU peak and achieved GB/s vs the
    819 GB/s HBM peak (XLA's own flops / bytes-accessed cost model; on
    a CPU backend the fractions are labeled by the section's backend
    key and are structure checks, not chip numbers)."""
    import jax

    t = _timeit(lambda: jax.tree_util.tree_map(
        lambda x: x.block_until_ready(), compiled(*args)))
    flops, bts = _cost_rows(compiled)
    row = {"program": name, "ms": round(t * 1e3, 3)}
    if flops:
        row["gflops_achieved"] = round(flops / t / 1e9, 2)
        row["mfu_vs_bf16_peak"] = round(
            flops / t / (PEAK_BF16_TFLOPS * 1e12), 5)
    if bts:
        row["gbps_achieved"] = round(bts / t / 1e9, 2)
        row["hbm_frac_of_peak"] = round(bts / t / (PEAK_HBM_GBPS * 1e9), 5)
    if flops and bts:
        # which peak the program sits closer to at this timing
        row["bound"] = ("compute" if row["mfu_vs_bf16_peak"]
                        >= row["hbm_frac_of_peak"] else "memory")
        row["arith_intensity_flops_per_byte"] = round(flops / bts, 2)
    if extra:
        row.update(extra)
    return row


def section_roofline(results: dict) -> None:
    """MFU / roofline placement of every hot program (VERDICT r3 item
    1: 'achieved GOP/s vs 197 TFLOP/s bf16 and achieved GB/s vs 819
    GB/s per kernel'). FLOP and byte counts come from XLA's compiled
    cost model (not hand math), times from warmed dispatches of the
    production configurations."""
    import jax
    import jax.numpy as jnp

    from gelly_streaming_tpu.ops.triangles import (TriangleWindowKernel,
                                                   _dense_row_counts,
                                                   intersect_local,
                                                   intersect_local_bsearch)

    rows = []
    # --- the streaming window program at both bench buckets, exactly
    # as the bench dispatches it (tuned K, tuned/compile-capped chunk —
    # the 64×32768 program wedged the tunnel's remote compiler, see
    # ops/triangles._default_chunk)
    for eb in (8_192, 32_768):
        vb = 2 * eb
        kern = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb)
        num_w = kern.MAX_STREAM_WINDOWS
        src, dst = _stream(num_w * eb, vb)
        from gelly_streaming_tpu.ops import segment as seg_ops

        _, s, d, valid = seg_ops.window_stack(src, dst, kern.eb,
                                              sentinel=kern.vb)
        ex = kern._stream_exec(num_w)
        args = (jnp.asarray(s[:num_w]), jnp.asarray(d[:num_w]),
                jnp.asarray(valid[:num_w]))
        rows.append(_roofline_row(
            "window_stream_eb%d" % eb, ex, args,
            {"k_bucket": kern.kb, "windows": num_w,
             "edges_per_s": None}))
        # fill the throughput key from the measured ms
        rows[-1]["edges_per_s"] = round(
            num_w * eb / (rows[-1]["ms"] / 1e3))

    # --- the two intersect lowerings at the profile shape
    ep, k, vbi = 16_384, 256, 1 << 16
    rng = np.random.default_rng(3)
    fill = rng.integers(0, vbi, size=(vbi + 1, k), dtype=np.int32)
    fill.sort(axis=1)
    keep = np.arange(k) < k // 4
    nbr = jnp.asarray(np.where(keep[None, :], fill, vbi).astype(np.int32))
    ea = jnp.asarray(rng.integers(0, vbi, size=ep, dtype=np.int32))
    eb_ = jnp.asarray(rng.integers(0, vbi, size=ep, dtype=np.int32))
    em = jnp.ones(ep, bool)
    for name, fn in (("intersect_compare", intersect_local),
                     ("intersect_bsearch", intersect_local_bsearch)):
        ex = jax.jit(fn).lower(nbr, ea, eb_, em).compile()
        rows.append(_roofline_row(name, ex, (nbr, ea, eb_, em),
                                  {"ep": ep, "k": k}))

    # --- the dense MXU path at its cutover size
    v = 2048
    e = 16 * v
    rng = np.random.default_rng(5)
    ds = jnp.asarray(rng.integers(0, v, size=e, dtype=np.int32))
    dd = jnp.asarray(rng.integers(0, v, size=e, dtype=np.int32))
    ex = jax.jit(_dense_row_counts, static_argnums=2).lower(
        ds, dd, v).compile()
    rows.append(_roofline_row("dense_matmul_v%d" % v, ex, (ds, dd),
                              {"v": v}))
    results["roofline"] = {
        "peaks": {"bf16_tflops": PEAK_BF16_TFLOPS,
                  "hbm_gbps": PEAK_HBM_GBPS, "hw": "tpu v5e (public)"},
        "rows": rows,
    }


def section_trace(results: dict) -> None:
    """Device trace of one production 64-window stream dispatch
    (VERDICT r3 item 1: 'a device_trace of one 64-window chunk').
    Captures a jax.profiler trace to logs/device_trace_<backend>/ and
    commits the parsed per-op time breakdown (top ops by total device
    time from the Chrome-trace export) into PERF.json — the raw xplane
    stays in logs/ as the artifact."""
    import glob
    import gzip

    import jax
    import jax.numpy as jnp

    from gelly_streaming_tpu.ops import segment as seg_ops
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    eb = 32_768
    vb = 2 * eb
    kern = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb)
    # the production chunk (compile-capped on the tunnel: the 64×32768
    # program wedged the remote compiler — ops/triangles._default_chunk)
    num_w = kern.MAX_STREAM_WINDOWS
    src, dst = _stream(num_w * eb, vb)
    _, s, d, valid = seg_ops.window_stack(src, dst, kern.eb,
                                          sentinel=kern.vb)
    ex = kern._stream_exec(num_w)
    args = (jnp.asarray(s[:num_w]), jnp.asarray(d[:num_w]),
            jnp.asarray(valid[:num_w]))
    for _ in range(2):  # warm: compile + first-dispatch noise out
        ex(*args)[0].block_until_ready()
    tdir = os.path.join(REPO, "logs",
                        "device_trace_%s" % jax.default_backend())
    os.makedirs(tdir, exist_ok=True)
    jax.profiler.start_trace(tdir)
    t0 = time.perf_counter()
    ex(*args)[0].block_until_ready()
    wall = time.perf_counter() - t0
    jax.profiler.stop_trace()

    # parse the Chrome-trace export: total device time by op name
    tops, err = [], None
    try:
        traces = sorted(glob.glob(os.path.join(
            tdir, "plugins", "profile", "*", "*.trace.json.gz")),
            key=os.path.getmtime)
        with gzip.open(traces[-1], "rt") as f:
            events = json.load(f).get("traceEvents", [])
        by_name = {}
        for ev in events:
            if ev.get("ph") == "X" and ev.get("dur"):
                rec = by_name.setdefault(ev["name"], [0.0, 0])
                rec[0] += ev["dur"] / 1e3  # us -> ms
                rec[1] += 1
        tops = [{"op": n, "total_ms": round(ms, 3), "calls": c}
                for n, (ms, c) in sorted(by_name.items(),
                                         key=lambda kv: -kv[1][0])[:15]]
    except Exception as e:  # trace format drift must not sink the run
        err = "trace parse failed: %r" % e
    results["trace"] = {
        "edge_bucket": eb, "windows": num_w, "k_bucket": kern.kb,
        "dispatch_wall_ms": round(wall * 1e3, 3),
        "trace_dir": os.path.relpath(tdir, REPO),
        "top_ops": tops,
        **({"parse_error": err} if err else {}),
    }


def section_host_stream(results: dict) -> None:
    """Vectorized numpy window tier vs the device (XLA) stream kernel
    on THIS backend — the committed evidence `_resolve_stream_impl`
    reads. On a CPU backend both forms run the same single core and
    the rows drive the process-wide CPU fallback tier. On a chip the
    rows drive PER-EDGE-BUCKET routing of production
    count_stream/count_windows traffic (VERDICT r4 item 5: small
    dispatch-latency-bound windows route to the measured host tier) —
    so a chip row taken under host load mis-routes real traffic;
    keep the tunnel host quiet during this section."""
    import jax

    from gelly_streaming_tpu.ops import host_triangles
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    from gelly_streaming_tpu import native

    sizes = (8_192, 32_768)
    if jax.default_backend() == "cpu":
        sizes = sizes + (65_536,)
    out = []
    for eb in sizes:
        vb = 2 * eb
        num_w = 32
        src, dst = _stream(num_w * eb, vb)
        kern = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb)
        dev = kern._count_stream_device(src, dst)   # compile + warm
        host = host_triangles.count_stream(src, dst, eb)
        t_dev = _timeit(lambda: kern._count_stream_device(src, dst),
                        reps=3, warmup=0)
        t_host = _timeit(lambda: host_triangles.count_stream(
            src, dst, eb), reps=3, warmup=0)
        row = {
            "edge_bucket": eb, "windows": num_w,
            "parity": host == dev,
            "host_edges_per_s": round(num_w * eb / t_host),
            "device_edges_per_s": round(num_w * eb / t_dev),
            "host_vs_device": round(t_dev / t_host, 2),
        }
        if native.triangles_available():
            # the C++ compact-forward tier (native/ingest.cpp) competes
            # under the same committed-evidence rule
            nat = native.triangle_count_stream(src, dst, eb)
            t_nat = _timeit(lambda: native.triangle_count_stream(
                src, dst, eb), reps=3, warmup=0)
            row["native_parity"] = list(nat) == dev
            row["native_edges_per_s"] = round(num_w * eb / t_nat)
        out.append(row)
    results["host_stream"] = out


def section_pipeline(results: dict) -> None:
    """Per-stage (prep ms / h2d ms / compute ms per chunk)
    decomposition of the pipelined stream dispatch
    (ops/ingress_pipeline.StageTimers) plus a pipelined-vs-forced-sync
    A/B of the device path at both bench buckets and both wire
    formats — committed so the next tunnel window can decompose the
    chip-side wall (host prep vs transfer vs compute) without new
    instrumentation. Counts parity is asserted into the row, never
    assumed."""
    from gelly_streaming_tpu.ops import compact_ingress, ingress_pipeline
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel
    from gelly_streaming_tpu.ops.windowed_reduce import WindowedEdgeReduce

    rows = []
    for eb, ingress in ((8_192, "standard"), (32_768, "standard"),
                        (32_768, "compact")):
        vb = 2 * eb
        if ingress == "compact" and not compact_ingress.supports(vb):
            continue
        num_w = 64
        src, dst = _stream(num_w * eb, vb)
        kern = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb,
                                    ingress=ingress)
        got = {}

        def run_pipe():
            got["pipe"] = kern._count_stream_device(src, dst)

        def run_sync():
            with ingress_pipeline.forced_sync():
                got["sync"] = kern._count_stream_device(src, dst)

        run_pipe()                       # compile + warm
        kern.stage_timers.reset()        # timers cover timed reps only
        t_pipe = _timeit(run_pipe, reps=3, warmup=0)
        snap = kern.stage_timers.snapshot()
        t_sync = _timeit(run_sync, reps=3, warmup=0)
        row = {
            "engine": "triangle_stream", "edge_bucket": eb,
            "ingress": ingress, "windows": num_w,
            "windows_per_dispatch": kern.MAX_STREAM_WINDOWS,
            "workers": ingress_pipeline.worker_count(),
            "parity": got["pipe"] == got["sync"],
            "pipelined_edges_per_s": round(num_w * eb / t_pipe),
            "sync_edges_per_s": round(num_w * eb / t_sync),
            "pipeline_speedup": round(t_sync / t_pipe, 2),
            **snap,
        }
        rows.append(row)
        print(json.dumps({"pipeline_progress": row}), flush=True)

    # one windowed-reduce row: the second engine routed through the
    # pipeline (BASELINE config #2's device path)
    eb, nv, num_w = 8_192, 16_384, 64
    src, dst = _stream(num_w * eb, nv)
    val = (1 + (src + 3 * dst) % 97).astype(np.int32)
    eng = WindowedEdgeReduce(vertex_bucket=nv, edge_bucket=eb,
                             name="sum", direction="out")
    s64, d64 = src.astype(np.int64), dst.astype(np.int64)
    got = {}

    def r_pipe():
        got["pipe"] = eng._device_process_stream(s64, d64, val)

    def r_sync():
        with ingress_pipeline.forced_sync():
            got["sync"] = eng._device_process_stream(s64, d64, val)

    r_pipe()
    eng.stage_timers.reset()
    t_pipe = _timeit(r_pipe, reps=3, warmup=0)
    snap = eng.stage_timers.snapshot()
    t_sync = _timeit(r_sync, reps=3, warmup=0)
    rows.append({
        "engine": "windowed_reduce", "edge_bucket": eb,
        "ingress": eng.ingress, "windows": num_w,
        "workers": ingress_pipeline.worker_count(),
        "parity": all(
            np.array_equal(ca, cb) and np.array_equal(na, nb)
            for (ca, na), (cb, nb) in zip(got["pipe"], got["sync"])),
        "pipelined_edges_per_s": round(num_w * eb / t_pipe),
        "sync_edges_per_s": round(num_w * eb / t_sync),
        "pipeline_speedup": round(t_sync / t_pipe, 2),
        **snap,
    })
    print(json.dumps({"pipeline_progress": rows[-1]}), flush=True)
    results["pipeline_stages"] = rows


def section_host_reduce(results: dict) -> None:
    """Columnar windowed-reduce tiers (ops/windowed_reduce.py): device
    segment kernels vs the vectorized host kernel, per monoid — the
    committed evidence `_resolve_reduce_impl` reads (BASELINE config
    #2's engine). Parity asserted row by row before timing."""
    import numpy as np

    from gelly_streaming_tpu.ops.windowed_reduce import WindowedEdgeReduce

    from gelly_streaming_tpu import native

    rows = []
    for name, eb in (("sum", 8_192), ("sum", 32_768), ("min", 8_192)):
        nv = 2 * eb
        num_w = 32
        src, dst = _stream(num_w * eb, nv)
        val = (1 + (src + 3 * dst) % 97).astype(np.int32)
        eng = WindowedEdgeReduce(vertex_bucket=nv, edge_bucket=eb,
                                 name=name, direction="out")
        dev = eng._device_process_stream(src, dst, val)   # compile+warm
        host = eng._host_process_stream(src, dst, val)
        parity = all(
            (np.array_equal(hc[:nv], dc[:nv])
             if name == "sum" else
             np.array_equal(hc[:nv][hn[:nv] > 0], dc[:nv][dn[:nv] > 0]))
            and np.array_equal(hn[:nv], dn[:nv])
            for (dc, dn), (hc, hn) in zip(dev, host))
        t_dev = _timeit(lambda: eng._device_process_stream(
            src, dst, val), reps=3, warmup=0)
        t_host = _timeit(lambda: eng._host_process_stream(
            src, dst, val), reps=3, warmup=0)
        row = {
            "name": name, "edge_bucket": eb, "windows": num_w,
            "parity": parity,
            "host_edges_per_s": round(num_w * eb / t_host),
            "device_edges_per_s": round(num_w * eb / t_dev),
            "host_vs_device": round(t_dev / t_host, 2),
        }
        if native.windowed_reduce_available():
            # the C++ fused tier competes under the same committed-
            # evidence rule (it currently LOSES to the per-window
            # bincount form on this host — the honest row keeps it
            # deselected)
            nat = eng._native_process_stream(src, dst, val)
            row["native_parity"] = nat is not None and all(
                (np.array_equal(nc[:nv], hc[:nv]) if name == "sum"
                 else np.array_equal(nc[:nv][hn[:nv] > 0],
                                     hc[:nv][hn[:nv] > 0]))
                and np.array_equal(nn[:nv], hn[:nv])
                for (nc, nn), (hc, hn) in zip(nat, host))
            t_nat = _timeit(lambda: eng._native_process_stream(
                src, dst, val), reps=3, warmup=0)
            row["native_edges_per_s"] = round(num_w * eb / t_nat)
        rows.append(row)
    results["host_reduce"] = rows


def section_sharded(out_path: str) -> dict:
    """Run the sharded engines on the virtual 8-device CPU mesh in a
    subprocess (the backend pin must precede jax import)."""
    code = r"""
import json, sys, time
import numpy as np
sys.path.insert(0, %r)
from gelly_streaming_tpu.core.platform import cpu_mesh
cpu_mesh(8)
from bench import make_stream
from gelly_streaming_tpu.parallel.mesh import make_mesh
from gelly_streaming_tpu.parallel.sharded import (ShardedSummaryEngine,
                                                  ShardedTriangleWindowKernel)

mesh = make_mesh()
eb, vb, num_w = 8192, 16384, 16
src, dst = make_stream(num_w * eb, vb)
out = {}
for name, eng in (
    ("sharded_triangles", ShardedTriangleWindowKernel(
        mesh, edge_bucket=eb, vertex_bucket=vb)),
    ("sharded_fused", ShardedSummaryEngine(
        mesh, edge_bucket=eb, vertex_bucket=vb)),
):
    run = (eng.count_stream if hasattr(eng, "count_stream")
           else eng.process)
    def call():
        if hasattr(eng, "reset"):
            eng.reset()
        run(src, dst)
    call()  # compile
    ts = []
    for _ in range(3):
        t0 = time.perf_counter(); call(); ts.append(time.perf_counter() - t0)
    t = float(np.median(ts))
    out[name] = {"edge_bucket": eb, "windows": num_w, "devices": 8,
                 "backend": "cpu-virtual-mesh",
                 "per_window_ms": round(t / num_w * 1e3, 3),
                 "edges_per_s": round(num_w * eb / t)}

# owner vs replicated neighbor-row distribution (drives
# resolve_table_mode): wall-clock at a small-table shape AND the
# 10M-scale bucket shape (the VERDICT-flagged risk case), plus the
# analytic ICI accounting. The top-level *_edges_per_s keys carry the
# LARGE config — the decisive row for the selection.
from gelly_streaming_tpu.parallel.sharded import (ici_time_model,
                                                  window_collective_bytes)

tbl = {"devices": 8, "backend": "cpu-virtual-mesh", "rows": []}
for ceb, cvb, cw in ((8192, 16384, 16), (65536, 262144, 2)):
    csrc, cdst = make_stream(cw * ceb, cvb)
    row = {"edge_bucket": ceb, "vertex_bucket": cvb, "windows": cw}
    counts = {}
    for mode in ("replicated", "owner"):
        k = ShardedTriangleWindowKernel(mesh, edge_bucket=ceb,
                                        vertex_bucket=cvb, table=mode)
        counts[mode] = k.count_stream(csrc, cdst)   # compile + warm
        ts = []
        for _ in range(3):   # median of 3: a single sample on this
            t0 = time.perf_counter()   # loaded host could flip the
            k.count_stream(csrc, cdst)  # 5-percent bar by noise
            ts.append(time.perf_counter() - t0)
        t = float(np.median(ts))
        row[mode + "_edges_per_s"] = round(cw * ceb / t)
        b = window_collective_bytes(8, k.vb, k.kb, k.cap, mode)
        row[mode + "_ici_bytes_per_window"] = round(b["total"])
        b5 = ici_time_model(b)
        row[mode + "_ici_ms_v5e_model"] = round(b5["total"] * 1e3, 3)
    row["counts_match"] = counts["replicated"] == counts["owner"]
    tbl["rows"].append(row)
big = tbl["rows"][-1]
tbl["owner_edges_per_s"] = big["owner_edges_per_s"]
tbl["replicated_edges_per_s"] = big["replicated_edges_per_s"]
tbl["counts_match"] = all(r["counts_match"] for r in tbl["rows"])
out["sharded_table"] = tbl

# ---- per-collective measured-vs-modeled breakdown (VERDICT r3 item 7):
# each collective of build_sharded_window_counter microbenched ALONE at
# the exact shapes of the 10M-scale config (eb=65536, vb=262144), next
# to the analytic per-chip ICI bytes (window_collective_bytes) and the
# v5e ICI time model. On this virtual CPU mesh the measured column is
# shared-memory copy/dispatch time — a STRUCTURE validation; the same
# rows become the real ICI validation the day a multi-chip mesh exists.
import functools
import jax
from jax.sharding import PartitionSpec as P
try:
    from jax import shard_map
except ImportError:
    from jax.experimental.shard_map import shard_map
from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS
from gelly_streaming_tpu.parallel.sharded import ici_time_model

n = 8
big_kern = ShardedTriangleWindowKernel(mesh, edge_bucket=65536,
                                       vertex_bucket=262144)
cvb, ckb, ccap = big_kern.vb, big_kern.kb, big_kern.cap
kslice = ckb // n
m = n * ccap
ax = SHARD_AXIS
rng = np.random.default_rng(11)


def smap(body, in_specs, out_specs):
    # check_vma off: these are timing microbenches of single collectives
    # (all_gather's per-shard-identical output is not provably
    # replicated to the vma checker without a no-op collective, which
    # would pollute the very timing being measured)
    try:
        wrapped = functools.partial(
            shard_map, mesh=mesh, in_specs=in_specs,
            out_specs=out_specs, check_vma=False)(body)
    except TypeError:   # older shard_map: no check_vma kwarg
        wrapped = functools.partial(
            shard_map, mesh=mesh, in_specs=in_specs,
            out_specs=out_specs)(body)
    return jax.jit(wrapped)


def t_of(fn, *args):
    import jax.numpy as jnp
    jargs = tuple(jnp.asarray(a) for a in args)
    r = fn(*jargs)
    jax.block_until_ready(r)   # compile + warm
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*jargs))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts)) * 1e3


def a2a(x):
    return jax.lax.all_to_all(x, ax, split_axis=0, concat_axis=0,
                              tiled=True)


progs = {
    "psum_degrees": (
        smap(lambda x: jax.lax.psum(x[0], ax), (P(ax),), P()),
        [rng.integers(0, 9, size=(n, cvb + 1), dtype=np.int32)]),
    "all_to_all_pairs": (
        smap(lambda x, y: (a2a(x), a2a(y)), (P(ax), P(ax)),
             (P(ax), P(ax))),
        [rng.integers(0, cvb, size=(n * n, ccap), dtype=np.int32),
         rng.integers(0, cvb, size=(n * n, ccap), dtype=np.int32)]),
    "pmax_table": (
        smap(lambda x: jax.lax.pmax(x[0], ax), (P(ax),), P()),
        [np.zeros((n, cvb + 1, ckb), np.int32)]),
    "all_gather_row_ids": (
        smap(lambda x: jax.lax.all_gather(x, ax), (P(ax),), P()),
        [rng.integers(0, cvb, size=n * 2 * m, dtype=np.int32)]),
    "all_to_all_row_slices": (
        smap(a2a, (P(ax),), P(ax)),
        [rng.integers(-1, cvb, size=(n * n, 2 * m, kslice),
                      dtype=np.int32)]),
    "psum_count_and_overflow": (
        smap(lambda x: jax.lax.psum(x[0], ax), (P(ax),), P()),
        [rng.integers(0, 9, size=(n, 3), dtype=np.int32)]),
}
from gelly_streaming_tpu.parallel.sharded import window_collective_bytes
model_r = window_collective_bytes(n, cvb, ckb, ccap, "replicated")
model_o = window_collective_bytes(n, cvb, ckb, ccap, "owner")
model = dict(model_o); model.update(model_r)
tmodel = ici_time_model(model)
coll_rows = []
for cname, (prog, args) in progs.items():
    row = {
        "collective": cname,
        "modeled_ici_bytes_per_chip": round(model[cname]),
        "modeled_ms_v5e_ici": round(tmodel[cname] * 1e3, 4),
    }
    try:   # one collective's lowering quirk must not sink the section
        row["measured_ms_cpu_mesh"] = round(t_of(prog, *args), 3)
    except Exception as exc:
        row["error"] = repr(exc)[:300]
    coll_rows.append(row)
out["collectives"] = {
    "config": {"n": n, "vb": cvb, "kb": ckb, "cap": ccap,
               "edge_bucket": 65536},
    "backend": "cpu-virtual-mesh",
    "note": ("measured column is host shared-memory copy time on the "
             "virtual mesh; modeled columns are the exact per-chip ICI "
             "accounting to validate on real multi-chip hardware"),
    "rows": coll_rows,
}
print(json.dumps(out))
""" % REPO
    # PYTHONPATH is stripped so the baked sitecustomize can't dial the
    # (possibly wedged) PJRT relay from the CPU child; the code above
    # sys.path-inserts the repo itself. run_json_child gives the same
    # killpg-on-timeout contract as the chip sections.
    from bench import run_json_child

    from bench import clean_cpu_env

    env = clean_cpu_env(
        XLA_FLAGS="--xla_force_host_platform_device_count=8")
    return run_json_child([sys.executable, "-c", code], 1800, env=env)


def section_ingress_ab(results: dict) -> None:
    """Stream-chunk wire-format A/B (ops/compact_ingress.py) — the
    committed evidence `resolve_ingress` reads, via the same probes as
    the standalone tools/ingress_ab.py. `ingress_ab` carries ONLY the
    stream A/B rows (the selection gate checks parity+speedup on every
    row); the latency/bandwidth probes land under `ingress_probes`."""
    import jax
    import jax.numpy as jnp

    from tools.ingress_ab import (device_compute_probe, h2d_probe,
                                  latency_probe, stream_ab)

    probes, ab = [], []
    latency_probe(jax, jnp, probes)
    h2d_probe(jax, jnp, 32768, 16, probes)
    device_compute_probe(jax, jnp, probes)
    stream_ab(jax, jnp, int(os.environ.get("GS_AB_EDGES", 2_097_152)),
              ab)
    results["ingress_probes"] = probes
    results["ingress_ab"] = ab


def section_egress_ab(results: dict) -> None:
    """d2h egress-format A/B (ops/delta_egress.py) — the committed
    evidence `resolve_egress` reads, via the same probes as the
    standalone tools/egress_ab.py (exact parity asserted, median-of-3
    with dispersion committed). GS_AUTOTUNE is already pinned off for
    this child, so the egress lever is measured in isolation."""
    import jax

    from tools.egress_ab import driver_ab, reduce_ab

    rows = []
    edges = int(os.environ.get("GS_AB_EDGES", 524_288))
    driver_ab(jax, edges, rows)
    reduce_ab(jax, edges, rows)
    results["egress_ab"] = rows


def section_resident_ab(results: dict) -> None:
    """Resident-tier A/B (ops/resident_engine.py) — the committed
    evidence `resolve_resident` reads, via the same probes as the
    standalone tools/resident_ab.py: the donated super-batch
    megakernel vs chunked scan vs per-window scan dispatch (driver
    and summary engine), exact parity asserted, median-of-3 with
    dispersion. GS_AUTOTUNE is already pinned off for this child, so
    the residency lever is measured in isolation."""
    import jax

    from tools.resident_ab import driver_resident, engine_resident

    rows = []
    edges = int(os.environ.get("GS_AB_EDGES", 524_288))
    driver_resident(jax, edges, rows)
    engine_resident(jax, edges, rows)
    results["resident_ab"] = rows


def section_pallas_ab(results: dict) -> None:
    """Fused-window-megakernel A/B (ops/pallas_window.py) — the
    committed evidence `resolve_pallas_window` reads, via the same
    probes as the standalone tools/pallas_ab.py: Pallas megakernel vs
    XLA scan-of-gathers through the summary engine AND the triangle
    stream kernel, sha256 window parity against the host twins,
    median-of-3 with dispersion. GS_AUTOTUNE is already pinned off
    for this child, so the kernel lever is measured in isolation. On
    a CPU backend the kernel runs interpreted: the parity half of the
    row is real evidence, the speed half is not (and the
    backend-matched loader keeps it from driving a chip selection)."""
    import jax

    from tools.pallas_ab import engine_pallas, stream_pallas

    rows = []
    edges = int(os.environ.get("GS_AB_EDGES", 524_288))
    engine_pallas(jax, edges, rows)
    stream_pallas(jax, edges, rows)
    results["pallas_ab"] = rows


def section_autotune(results: dict) -> None:
    """Online dispatch-tuner evidence (ops/autotune.py): the triangle
    stream's device path static vs tuned-from-cold vs tuned-seeded
    (the second run starts from the first's persisted optimum), with
    the chosen arm and decision timeline committed — so the claim
    'the scheduler converges to a configuration no slower than the
    best static row' is a row, not an assertion."""
    import tempfile
    import time

    import numpy as np

    from bench import make_stream
    from gelly_streaming_tpu.ops import segment as seg_ops
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    eb, vb = 32768, 65536
    # the tuner engages only past one maximal dispatch chunk; give it
    # several rounds' worth of stream (≥4 chunks at the class default)
    edges = int(os.environ.get("GS_AUTOTUNE_EDGES", 8_388_608))
    src, dst = make_stream(edges, vb)

    def timed(fn):
        t0 = time.perf_counter()
        out = fn()
        return out, time.perf_counter() - t0

    os.environ["GS_AUTOTUNE"] = "0"
    k0 = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb)
    seg_ops.warm_stream_buckets(k0)
    base_counts = k0._count_stream_device(src, dst)  # warm run
    _, static_s = timed(lambda: k0._count_stream_device(src, dst))

    os.environ["GS_AUTOTUNE"] = "1"
    prev_cache = os.environ.get("GS_TUNE_CACHE")
    with tempfile.TemporaryDirectory(prefix="gs-tune-") as td:
        os.environ["GS_TUNE_CACHE"] = td  # cold, section-local cache
        try:
            k1 = TriangleWindowKernel(edge_bucket=eb,
                                      vertex_bucket=vb)
            counts1, cold_s = timed(
                lambda: k1._count_stream_device(src, dst))
            # a second kernel = a second process: seeds from the cache
            k2 = TriangleWindowKernel(edge_bucket=eb,
                                      vertex_bucket=vb)
            counts2, seeded_s = timed(
                lambda: k2._count_stream_device(src, dst))
        finally:
            if prev_cache is None:
                os.environ.pop("GS_TUNE_CACHE", None)
            else:
                os.environ["GS_TUNE_CACHE"] = prev_cache
    parity = base_counts == counts1 == counts2
    t2 = getattr(k2, "tuner", None)
    t1 = getattr(k1, "tuner", None)
    summary = t2.summary() if t2 else {}
    row = {
        "engine": "triangle_stream",
        "edge_bucket": eb, "vertex_bucket": vb, "num_edges": edges,
        "static_edges_per_s": round(edges / static_s),
        "tuned_cold_edges_per_s": round(edges / cold_s),
        "tuned_seeded_edges_per_s": round(edges / seeded_s),
        "seeded_vs_static": round(static_s / seeded_s, 3),
        "parity": bool(parity),
        "chosen": summary.get("chosen"),
        "rounds": summary.get("rounds"),
        "promotions": summary.get("promotions"),
        "cold_timeline": (t1.summary().get("timeline", [])
                          if t1 else []),
    }
    results["autotune"] = [row]


def section_telemetry(results: dict) -> None:
    """Flight-recorder evidence (utils/telemetry): the armed recorder
    on the 524K/32768 bench row must (a) change NO result — counts
    asserted identical to the disarmed run — and (b) cost little
    enough to leave on outside A/B sections (the armed/disarmed wall
    ratio is committed, bar <1.02). A driver leg then produces a full
    ledger that tools/trace_report.py round-trips (span table +
    Perfetto export), so the whole toolchain is exercised in the same
    window that commits the rows."""
    import tempfile

    from bench import make_stream
    from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel
    from gelly_streaming_tpu.utils import telemetry

    eb, vb = 32768, 65536
    edges = int(os.environ.get("GS_TELEMETRY_EDGES", 524288))
    src, dst = make_stream(edges, vb)
    prev = {k: os.environ.get(k)
            for k in ("GS_TELEMETRY", "GS_TRACE_DIR")}
    try:
        os.environ["GS_TELEMETRY"] = "0"
        kern = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb)
        base = kern.count_stream(src, dst)  # warm + baseline counts
        # 7-rep medians: the row is ~tens of ms on a CPU backend, so a
        # 3-rep median swings past the <2% overhead bar on host noise
        off_s = _timeit(lambda: kern.count_stream(src, dst),
                        reps=7, warmup=2)
        with tempfile.TemporaryDirectory(prefix="gs-trace-") as td:
            os.environ["GS_TELEMETRY"] = "1"
            os.environ["GS_TRACE_DIR"] = td
            telemetry.reset()
            armed = kern.count_stream(src, dst)
            if list(armed) != list(base):
                raise AssertionError(
                    "armed recorder changed the counts — the "
                    "zero-overhead contract is broken")
            on_s = _timeit(lambda: kern.count_stream(src, dst),
                           reps=7, warmup=1)
            # driver leg: the richer span tree + a real ledger the
            # report tool round-trips
            drv = StreamingAnalyticsDriver(
                window_ms=0, edge_bucket=eb, vertex_bucket=1024,
                analytics=("degrees", "cc", "bipartite"))
            drv.run_arrays(src, dst)
            rows = telemetry.summary(top=16)
            telemetry.flush()
            ledger = telemetry.ledger_path()
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "trace_report",
                os.path.join(REPO, "tools", "trace_report.py"))
            trace_report = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(trace_report)
            recs = trace_report.load(ledger)
            perfetto = trace_report.to_perfetto(recs)
            meta = {
                "engine": "triangle_stream+driver",
                "edge_bucket": eb, "num_edges": edges,
                "parity": True,
                "disarmed_edges_per_s": round(edges / off_s),
                "armed_edges_per_s": round(edges / on_s),
                "overhead_ratio": round(on_s / off_s, 3),
                "trace": telemetry.trace_id(),
                "ledger_records": len(recs),
                "perfetto_events": len(perfetto["traceEvents"]),
            }
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        telemetry.reset()
    results["telemetry"] = rows
    results["telemetry_meta"] = meta


def section_metrics(results: dict) -> None:
    """Metrics-plane evidence (utils/metrics): the armed registry on
    the 524K/32768 bench row must (a) change NO result — counts
    asserted identical to the disarmed run — and (b) stay under the
    1.05× armed-overhead bar (the plane records via the telemetry
    sink with GS_TELEMETRY=0: arming metrics never arms the ledger).
    The committed meta is the schema-validated `metrics` section
    (tools/perf_schema.py) the ISSUE-8 acceptance bar reads."""
    from bench import make_stream
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel
    from gelly_streaming_tpu.utils import metrics

    eb, vb = 32768, 65536
    edges = int(os.environ.get("GS_TELEMETRY_EDGES", 524288))
    src, dst = make_stream(edges, vb)
    prev = {k: os.environ.get(k)
            for k in ("GS_METRICS", "GS_TELEMETRY")}
    try:
        os.environ["GS_METRICS"] = "0"
        os.environ["GS_TELEMETRY"] = "0"
        kern = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb)
        base = kern.count_stream(src, dst)  # warm + baseline counts
        off_s = _timeit(lambda: kern.count_stream(src, dst),
                        reps=7, warmup=2)
        os.environ["GS_METRICS"] = "1"
        metrics.reset()
        armed = kern.count_stream(src, dst)
        if list(armed) != list(base):
            raise AssertionError(
                "armed metrics registry changed the counts — the "
                "zero-overhead contract is broken")
        on_s = _timeit(lambda: kern.count_stream(src, dst),
                       reps=7, warmup=1)
        snap = metrics.health_snapshot()
        prep = metrics.histogram("gs_stage_seconds", stage="prep")
        meta = {
            "engine": "triangle_stream",
            "edge_bucket": eb, "num_edges": edges,
            "parity": True,
            "disarmed_edges_per_s": round(edges / off_s),
            "armed_edges_per_s": round(edges / on_s),
            "overhead_ratio": round(on_s / off_s, 3),
            "health_status": snap["status"],
            "windows_observed": snap["windows_finalized"],
            "stage_prep_observations": (prep or {}).get("count", 0),
            "compiles": {name: c["count"]
                         for name, c in
                         metrics.compile_report().items()},
        }
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        metrics.reset()
    results["metrics"] = meta


def section_latency(results: dict) -> None:
    """Latency-plane evidence (utils/latency): the armed plane on the
    524K/32768 fused-scan row must (a) change NO summary — asserted
    identical to the disarmed run, (b) stay under the 1.05× armed-
    overhead bar, and (c) RECONCILE — every window's stage waterfall
    sums to its measured ingest→deliver end-to-end within 5% (the
    conservation contract tools/latency_report.py re-checks from
    ledgers). The committed meta is the schema-validated `latency`
    section (tools/perf_schema.py) the acceptance bar reads; its
    e2e_p{50,95,99}_s fields feed bench_compare's lower-is-better
    comparisons."""
    from bench import make_stream
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)
    from gelly_streaming_tpu.utils import latency

    eb, vb = 32768, 65536
    edges = int(os.environ.get("GS_TELEMETRY_EDGES", 524288))
    src, dst = make_stream(edges, vb)
    prev = {k: os.environ.get(k)
            for k in ("GS_LATENCY", "GS_METRICS", "GS_TELEMETRY")}
    try:
        os.environ["GS_LATENCY"] = "0"
        os.environ["GS_METRICS"] = "0"
        os.environ["GS_TELEMETRY"] = "0"
        eng = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)

        def run():
            eng.reset()
            return eng.process(src, dst)

        base = run()  # warm + baseline summaries
        off_s = _timeit(run, reps=5, warmup=1)
        os.environ["GS_LATENCY"] = "1"
        latency.reset()
        armed = run()
        if armed != base:
            raise AssertionError(
                "armed latency plane changed the summaries — the "
                "zero-overhead contract is broken")
        on_s = _timeit(run, reps=5, warmup=1)
        recs = latency.recent()
        if not recs:
            raise AssertionError("armed run recorded no windows")
        worst = 0.0
        for rec in recs:
            ok, gap = latency.reconcile(rec)
            if not ok:
                raise AssertionError(
                    "waterfall does not reconcile: window %s gap "
                    "%.6fs of %.6fs e2e" % (rec["window"], gap,
                                            rec["e2e_s"]))
            if rec["e2e_s"] > 0:
                worst = max(worst, gap / rec["e2e_s"])
        stage_totals = {}
        for rec in recs:
            for name, dur in rec["stages"].items():
                stage_totals[name] = stage_totals.get(name, 0) + dur
        meta = {
            "engine": "fused_scan",
            "edge_bucket": eb, "num_edges": edges,
            "parity": True,
            "disarmed_edges_per_s": round(edges / off_s),
            "armed_edges_per_s": round(edges / on_s),
            "overhead_ratio": round(on_s / off_s, 3),
            "reconciled_windows": len(recs),
            "max_unaccounted_frac": round(worst, 6),
            "stages_total_s": {k: round(v, 6) for k, v in
                               sorted(stage_totals.items())},
            **latency.percentile_fields("e2e"),
        }
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        latency.reset()
    results["latency"] = meta


def section_sanitize(results: dict) -> None:
    """Admission-sanitizer evidence (utils/sanitize): the armed
    sanitizer on the 524K/32768 fused-scan row must (a) change NO
    summary on a clean stream — asserted identical to the disarmed
    run, and (b) stay under the 1.02× armed-overhead bar (the
    sanitizer is a handful of vectorized numpy passes against seconds
    of scan work). The committed meta is the schema-validated
    `sanitize` section (tools/perf_schema.py); its dlq_records /
    quarantines counters feed bench_compare's not-worse checks — a
    clean row must commit both at 0."""
    from bench import make_stream
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)
    from gelly_streaming_tpu.utils import resilience as _resilience
    from gelly_streaming_tpu.utils import sanitize as _sanitize

    eb, vb = 32768, 65536
    edges = int(os.environ.get("GS_TELEMETRY_EDGES", 524288))
    src, dst = make_stream(edges, vb)
    prev = {k: os.environ.get(k)
            for k in ("GS_SANITIZE", "GS_DLQ_DIR")}
    try:
        os.environ["GS_SANITIZE"] = "off"
        os.environ.pop("GS_DLQ_DIR", None)
        eng = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)

        def run():
            eng.reset()
            return eng.process(src, dst)

        base = run()  # warm + baseline summaries
        off_s = _timeit(run, reps=5, warmup=1)
        # mode `on` (structural checks): inert on a clean in-range
        # stream by construction. `strict` is a POLICY change (it
        # rejects self-loops, which a random stream contains), so
        # parity is only a contract for `on`.
        os.environ["GS_SANITIZE"] = "on"
        armed = run()
        if armed != base:
            raise AssertionError(
                "armed sanitizer changed a clean stream's summaries "
                "— the inert-on-clean contract is broken")
        on_s = _timeit(run, reps=5, warmup=1)
        dlq = _sanitize.dlq_status()
        meta = {
            "engine": "fused_scan",
            "edge_bucket": eb, "num_edges": edges,
            "mode": "on",
            "parity": True,
            "disarmed_edges_per_s": round(edges / off_s),
            "armed_edges_per_s": round(edges / on_s),
            "overhead_ratio": round(on_s / off_s, 3),
            "dlq_records": 0 if dlq is None else int(dlq["records"]),
            "quarantines": sum(
                1 for e in _resilience.demotion_events()
                if e.get("to") == "quarantined"),
        }
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    results["sanitize"] = meta


def section_provenance(results: dict) -> None:
    """Provenance-ledger evidence (utils/provenance): arming the
    per-window ledger on the 524K/32768 fused-scan row must (a)
    change NO summary — asserted identical to the disarmed run, (b)
    stay under the 1.02× armed-overhead bar (one canonical-JSON
    record + CRC frame + fsync per 32768-edge window against seconds
    of scan work), and (c) record the TRUTH — every armed window's
    ledger digest is asserted equal to the sha256 of the disarmed
    baseline's summary, so the committed row proves the audit trail
    describes the windows it claims to. Also commits the per-tenant
    attribution evidence rows (utils/metrics.attribute_dispatch): a
    fixed 4-row dispatch split whose tenant seconds reconcile to the
    span total bit-for-bit, pad row attributing zero."""
    import tempfile

    from bench import make_stream
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)
    from gelly_streaming_tpu.utils import metrics as _metrics
    from gelly_streaming_tpu.utils import provenance as _prov

    eb, vb = 32768, 65536
    edges = int(os.environ.get("GS_TELEMETRY_EDGES", 524288))
    src, dst = make_stream(edges, vb)
    prev = {k: os.environ.get(k)
            for k in ("GS_PROVENANCE", "GS_PROVENANCE_DIR",
                      "GS_METRICS", "GS_LATENCY", "GS_TELEMETRY")}
    prov_dir = tempfile.mkdtemp(prefix="gs_prov_perf_")
    try:
        os.environ["GS_PROVENANCE"] = "0"
        os.environ.pop("GS_PROVENANCE_DIR", None)
        os.environ["GS_METRICS"] = "0"
        os.environ["GS_LATENCY"] = "0"
        os.environ["GS_TELEMETRY"] = "0"
        eng = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)

        def run():
            eng.reset()
            return eng.process(src, dst)

        base = run()  # warm + baseline summaries
        off_s = _timeit(run, reps=5, warmup=1)
        os.environ["GS_PROVENANCE"] = "1"
        os.environ["GS_PROVENANCE_DIR"] = prov_dir
        armed = run()
        if armed != base:
            raise AssertionError(
                "armed provenance ledger changed the summaries — the "
                "zero-overhead contract is broken")
        on_s = _timeit(run, reps=5, warmup=1)
        _prov.reset()  # flush + close before auditing the segments
        sc = _prov.scan(prov_dir)
        if sc["torn"] is not None:
            raise AssertionError("armed run left a torn ledger tail "
                                 "in a clean shutdown: %r" % sc["torn"])
        # every rep re-emits windows 0..N-1 (reset() rewinds the
        # cursor): at-least-once duplicates must collapse cleanly
        keyed = {}
        for rec in sc["records"]:
            keyed[(rec["tenant"], rec["window"], rec["tier"])] = rec
        if len(keyed) != len(base):
            raise AssertionError(
                "armed run finalized %d windows but the ledger holds "
                "%d distinct records" % (len(base), len(keyed)))
        for (t, w, _tier), rec in sorted(keyed.items()):
            want = _prov.summary_digest(base[w])
            if rec["digest"] != want:
                raise AssertionError(
                    "ledger digest for window %d (%s != %s) does not "
                    "match the disarmed baseline summary"
                    % (w, rec["digest"], want))
        # attribution evidence (DESIGN.md §24): one armed dispatch
        # split across 4 tenant rows by valid edges — deterministic
        # fixed span so the committed rows are comparable run-to-run
        os.environ["GS_METRICS"] = "1"
        _metrics.reset()
        span_s = 0.25
        shares = _metrics.attribute_dispatch(
            span_s, [("hot", eb), ("warm", eb // 2),
                     ("pad", 0), ("cold", eb // 4)])
        _metrics.reset()
        attr_sum = sum(s for _t, s, _b in shares)
        if attr_sum != span_s:
            raise AssertionError(
                "attributed tenant seconds (%.17g) do not reconcile "
                "to the dispatch span (%.17g)" % (attr_sum, span_s))
        meta = {
            "engine": "fused_scan",
            "edge_bucket": eb, "num_edges": edges,
            "parity": True,
            "disarmed_edges_per_s": round(edges / off_s),
            "armed_edges_per_s": round(edges / on_s),
            "overhead_ratio": round(on_s / off_s, 3),
            "records": len(sc["records"]),
            "windows_verified": len(keyed),
            "segments": int(sc["segments"]),
            "knob_fingerprint": _prov.knob_fingerprint(),
            "attribution": {
                "span_s": span_s,
                "reconciles": True,
                "rows": [{"tenant": t, "device_s": round(s, 9),
                          "share": round(s / span_s, 6)}
                         for t, s, _b in shares],
            },
        }
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _prov.reset()
    results["provenance"] = meta


def section_cost_model(results: dict) -> None:
    """Program cost observatory evidence (utils/costmodel): capture
    XLA cost_analysis-derived FLOPs/bytes for the three hot stream
    programs — the triangle stream executable, the fused scan, and
    the resident super-batch — on the 524K/32768 row, joined with the
    measured dispatch spans of an armed flight-recorder run whose
    ledger is COMMITTED (logs/costmodel_ledger_cpu.jsonl) so
    tools/explain_perf.py has a real attribution substrate in tier-1.
    Results are asserted digest-identical armed vs disarmed (the
    observatory observes, never participates)."""
    import hashlib
    import shutil
    import tempfile

    from bench import make_stream
    from gelly_streaming_tpu.ops.resident_engine import (
        ResidentSummaryEngine)
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel
    from gelly_streaming_tpu.utils import costmodel, knobs, telemetry

    eb, vb = 32768, 65536
    edges = int(os.environ.get("GS_TELEMETRY_EDGES", 524288))
    src, dst = make_stream(edges, vb)

    def digest(obj):
        return hashlib.sha256(json.dumps(
            obj, sort_keys=True, default=int).encode()).hexdigest()

    prev = {k: os.environ.get(k)
            for k in ("GS_COSTMODEL", "GS_TELEMETRY", "GS_TRACE_DIR")}
    try:
        os.environ["GS_COSTMODEL"] = "0"
        os.environ["GS_TELEMETRY"] = "0"
        kern = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb)
        eng = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
        res = ResidentSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
        base = {
            "triangle_stream": list(kern._count_stream_device(src,
                                                              dst)),
            "fused_scan": eng.process(src, dst),
            "resident": res.process(src, dst),
        }
        with tempfile.TemporaryDirectory(prefix="gs-costmodel-") as td:
            os.environ["GS_COSTMODEL"] = "1"
            os.environ["GS_TELEMETRY"] = "1"
            os.environ["GS_TRACE_DIR"] = td
            telemetry.reset()
            costmodel.reset()
            eng.reset()
            res.reset()
            armed = {
                "triangle_stream": list(
                    kern._count_stream_device(src, dst)),
                "fused_scan": eng.process(src, dst),
                "resident": res.process(src, dst),
            }
            for leg in base:
                if digest(base[leg]) != digest(armed[leg]):
                    raise AssertionError(
                        "armed cost observatory changed the %s "
                        "results — the zero-overhead contract is "
                        "broken" % leg)
            rows = costmodel.report()
            trace = telemetry.trace_id()
            telemetry.flush()
            ledger_src = telemetry.ledger_path()
            ledger_rel = "logs/costmodel_ledger_cpu.jsonl"
            os.makedirs(os.path.join(REPO, "logs"), exist_ok=True)
            shutil.copyfile(ledger_src,
                            os.path.join(REPO, ledger_rel))
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        telemetry.reset()
        costmodel.reset()
    results["cost_model"] = {
        "engine": "triangle_stream+fused_scan+resident",
        "edge_bucket": eb,
        "num_edges": edges,
        "parity": True,
        "trace": trace,
        "ledger": ledger_rel,
        "peaks": {
            "gflops": knobs.get_float("GS_COSTMODEL_PEAK_GFLOPS"),
            "gbps": knobs.get_float("GS_COSTMODEL_PEAK_GBPS"),
        },
        "programs": rows,
    }


def section_gnn(results: dict) -> None:
    """The windowed-GNN cost observatory (ops/gnn_window): the same
    armed/disarmed evidence tools/gnn_ab.py --commit writes — digest
    parity asserted (armed ≡ disarmed ≡ numpy twin, slab AND
    summaries) before the analytic slab-model rows are kept. One
    shared helper so the profiler and the A/B tool can never commit
    divergent shapes for the same section."""
    from tools.gnn_ab import gnn_cost_section

    results["gnn"] = gnn_cost_section()


def section_host_snapshot(results: dict) -> None:
    """Batched snapshot-analytics tiers: the driver's device scan vs
    the C++ carried union-find (native.snapshot_windows) — the
    committed evidence core.driver.resolve_snapshot_tier reads.
    Window-by-window parity asserted before timing; rates are whole
    run_arrays batches (intern + snapshot + materialize), reset
    between reps so carried state restarts identically."""
    import numpy as np

    from gelly_streaming_tpu import native
    from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver

    rows = []
    for eb in (8_192, 65_536):
        vb = 4 * eb
        num_w = 16
        src, dst = _stream(num_w * eb, vb)
        kw = dict(window_ms=0, edge_bucket=eb, vertex_bucket=vb,
                  analytics=("degrees", "cc", "bipartite"))
        a = StreamingAnalyticsDriver(snapshot_tier="scan", **kw)
        dev = a.run_arrays(src, dst)
        row = {"edge_bucket": eb, "windows": num_w}
        if native.snapshot_available():
            b = StreamingAnalyticsDriver(snapshot_tier="native", **kw)
            nat = b.run_arrays(src, dst)
            row["parity"] = all(
                np.array_equal(x.degrees, y.degrees)
                and np.array_equal(x.cc_labels, y.cc_labels)
                and np.array_equal(x.bipartite_odd, y.bipartite_odd)
                for x, y in zip(dev, nat))

            def run(drv):
                drv.reset()
                drv.run_arrays(src, dst)

            t_dev = _timeit(lambda: run(a), reps=3, warmup=0)
            t_nat = _timeit(lambda: run(b), reps=3, warmup=0)
            row["scan_edges_per_s"] = round(num_w * eb / t_dev)
            row["native_edges_per_s"] = round(num_w * eb / t_nat)
            row["native_vs_scan"] = round(t_dev / t_nat, 2)
        rows.append(row)
    results["host_snapshot"] = rows


PROBE_TIMEOUT_S = int(os.environ.get("GS_PROBE_TIMEOUT", "420"))

# Candidate stream programs for the per-program compile caps
# (ops/triangles.compile_cap). Triangle candidates try to RAISE the
# 2^19 default (the chip chunk sweep was still climbing at the cap);
# scan candidates BISECT the fused/snapshot wedge (both programs
# stalled the remote compiler >2400s at sizes the triangle program
# compiles cleanly).
PROBE_CANDIDATES = {
    "compile_probe": [
        ("triangle_stream", 32_768, 32),   # 2^20
        ("triangle_stream", 8_192, 128),   # 2^20
    ],
    "compile_probe_scan": [
        ("fused_scan", 8_192, 16),         # 2^17
        ("fused_scan", 32_768, 16),        # 2^19 (the wedged shape?)
        ("snapshot_scan", 8_192, 16),      # 2^17
        ("snapshot_scan", 8_192, 32),      # 2^18 (the r04 driver shape)
        # structural bisection of the scan wedge (VERDICT r4 weak-7:
        # the caps are a tourniquet, not a diagnosis): the same 2^19
        # slot budget that wedges the 3-analytic snapshot scan, with
        # FEWER carried analytics. A clean deg-only row at a size the
        # full scan wedges pins the predicate to the multi-analytic
        # carry, not scan length; deg+cc in between splits the carry
        # axis. Diagnostic program keys — they never move the real
        # snapshot_scan cap.
        ("snapshot_scan_deg", 32_768, 16),     # 2^19, 1 analytic
        ("snapshot_scan_degcc", 32_768, 16),   # 2^19, 2 analytics
    ],
}


def run_compile_probe_child(program: str, eb: int, wb: int) -> None:
    """Compile (and for the scan programs, run once on a trivial
    stream) ONE candidate shape, overriding the memoized cap so the
    shape under test is actually built. Prints a single probe row;
    the orchestrating section's subprocess timeout converts a wedged
    remote compile into an ok=false row instead of a lost stage."""
    import jax

    import numpy as np

    from gelly_streaming_tpu.ops import triangles as tri

    t0 = time.perf_counter()
    tri._COMPILE_CAPS[program] = 1 << 30
    if program.startswith("snapshot_scan"):
        # the driver clamps its scan chunk by the BASE program's cap;
        # the diagnostic variants must still build the shape under test
        tri._COMPILE_CAPS["snapshot_scan"] = 1 << 30
    if program == "triangle_stream":
        k = tri.TriangleWindowKernel(edge_bucket=eb, vertex_bucket=2 * eb)
        k.MAX_STREAM_WINDOWS = wb
        k._stream_exec(wb)   # AOT compile only
    elif program == "fused_scan":
        from gelly_streaming_tpu.ops.scan_analytics import (
            StreamSummaryEngine)

        eng = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=2 * eb)
        eng.MAX_WINDOWS = wb
        z = np.zeros(wb * eb, np.int32)
        eng.process(z, np.ones(wb * eb, np.int32))
    elif program.startswith("snapshot_scan"):
        from gelly_streaming_tpu.core.driver import (
            StreamingAnalyticsDriver)

        analytics = {"snapshot_scan": ("degrees", "cc", "bipartite"),
                     "snapshot_scan_deg": ("degrees",),
                     "snapshot_scan_degcc": ("degrees", "cc")}.get(program)
        if analytics is None:
            raise SystemExit("unknown probe program %r" % program)
        drv = StreamingAnalyticsDriver(
            window_ms=0, edge_bucket=eb, vertex_bucket=2 * eb,
            analytics=analytics)
        drv._SCAN_CHUNK = wb
        z = np.zeros(wb * eb, np.int32)
        drv.run_arrays(z, np.ones(wb * eb, np.int32))
    else:
        raise SystemExit("unknown probe program %r" % program)
    print(json.dumps({
        "program": program, "eb": eb, "wb": wb, "slots": eb * wb,
        "ok": True, "compile_s": round(time.perf_counter() - t0, 1),
        "backend": jax.default_backend(),
    }), flush=True)


def _section_compile_probe(key: str, results: dict) -> None:
    import jax

    from bench import run_json_child

    backend = jax.default_backend()
    rows = []
    for program, eb, wb in PROBE_CANDIDATES[key]:
        got = run_json_child(
            [sys.executable, os.path.abspath(__file__), "--probe",
             program, str(eb), str(wb)], PROBE_TIMEOUT_S)
        row = {"program": program, "eb": eb, "wb": wb,
               "slots": eb * wb}
        err = str(got.get("error") or "")
        if got.get("ok") and got.get("backend") == backend:
            row.update(ok=True, compile_s=got.get("compile_s"))
        elif "timeout" in err.lower():
            # a timed-out compile is the wedge evidence compile_cap
            # LOWERS on
            row.update(ok=False, reason=err[:200])
        else:
            # crash / backend fell over mid-probe: inconclusive — never
            # lower a cap over a tunnel flake (ok stays non-boolean,
            # compile_cap ignores the row)
            row.update(ok=None,
                       reason=(err or "backend %s"
                               % got.get("backend"))[:200])
        rows.append(row)
        print(json.dumps(row), flush=True)
    results[key] = rows


def section_chunk_deep(results: dict) -> None:
    """Chunk sweep ABOVE the pre-probe compile cap. Runs after the
    compile_probe section in the same window: this child re-reads the
    just-flushed PERF.json, so a clean probe row at 2^20 raises
    capped_chunk here and the sweep measures windows-per-dispatch
    depths the window section's anchor-bounded sweep could not reach
    (r04: the chip sweep was still climbing — 962K edges/s at 16 —
    when it hit the 2^19 cap). Rows land under `chunk_deep` and merge
    into the runtime's chunk selection via
    ops/triangles._fastest_sweep_row, so the queue's next bench
    dispatches at the fastest measured depth."""
    from gelly_streaming_tpu.ops import triangles as tri

    out = []
    for eb in (32_768, 8_192):
        vb = 2 * eb
        cap_c = tri.capped_chunk(eb, "triangle_stream")
        perf = tri._load_matching_perf() or {}
        measured = [
            int(s["windows_per_dispatch"])
            for key in ("window", "chunk_deep")
            for row in perf.get(key, []) or []
            if row.get("edge_bucket") == eb
            for s in row.get("chunk_sweep", []) or []
            if s.get("windows_per_dispatch")]
        hi = max(measured, default=0)
        cands = sorted({c for c in (cap_c, cap_c // 2) if c > hi})
        row = {"edge_bucket": eb, "cap_chunk": cap_c,
               "measured_max": hi, "chunk_sweep": []}
        if not cands:
            row["note"] = "no candidates above measured depth"
            out.append(row)
            continue
        kern = tri.TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb)
        num_w = max(cands)
        src, dst = _stream(num_w * eb, vb, seed=8)
        row.update(k_bucket=kern.kb, windows=num_w)
        for cs in cands:
            kern.MAX_STREAM_WINDOWS = cs
            kern._count_stream_device(src, dst)  # compile + warm
            t = _timeit(lambda: kern._count_stream_device(src, dst),
                        reps=3, warmup=0)
            row["chunk_sweep"].append({
                "windows_per_dispatch": cs,
                "per_window_ms": round(t / num_w * 1e3, 3),
                "edges_per_s": round(num_w * eb / t),
            })
        out.append(row)
    results["chunk_deep"] = out


def section_compile_probe(results: dict) -> None:
    """Triangle-program cap-raise candidates (one subprocess each)."""
    _section_compile_probe("compile_probe", results)


def section_compile_probe_scan(results: dict) -> None:
    """Fused/snapshot scan wedge bisection (one subprocess each)."""
    _section_compile_probe("compile_probe_scan", results)


# Order = run order. EVERY wedge-prone compile runs LAST — including
# the cap-raise probes: killing a probing subprocess at its timeout
# does NOT un-wedge the tunnel's remote compile SERVICE (round 2: one
# oversized program stalled it for hours), so a probe placed early
# could cost every later section its 2400s against a dead compiler. A
# clean probe's raised cap therefore benefits the NEXT window's chunk
# sweep (the sweep anchors on _default_chunk, which reads committed
# caps); fused/driver still run after the probes in the SAME window,
# re-reading the just-flushed caps so they compile at probed-safe
# sizes instead of wedging >2400s as in r04.
SECTIONS = {
    "intersect": section_intersect,
    "ingress_ab": section_ingress_ab,
    "egress_ab": section_egress_ab,
    "autotune": section_autotune,
    "telemetry": section_telemetry,
    "metrics": section_metrics,
    "latency": section_latency,
    "sanitize": section_sanitize,
    "provenance": section_provenance,
    "window": section_window,
    "host_stream": section_host_stream,
    "pipeline_stages": section_pipeline,
    "host_reduce": section_host_reduce,
    "host_snapshot": section_host_snapshot,
    "compile_probe": section_compile_probe,
    "compile_probe_scan": section_compile_probe_scan,
    "chunk_deep": section_chunk_deep,
    "dense": section_dense,
    "roofline": section_roofline,
    "trace": section_trace,
    # resident_ab compiles snapshot-scan-family programs (the donated
    # super-batch form): wedge-prone on the tunneled chip, so it runs
    # with the other scan-class compiles at the END of the order
    "resident_ab": section_resident_ab,
    # pallas_ab compiles the megakernel-bodied scan programs (Mosaic
    # kernels inside a scan): scan-class compiles, END of the order
    # beside resident_ab
    "pallas_ab": section_pallas_ab,
    # cost_model AOT-compiles the fused-scan/resident programs once
    # more for their analyses: scan-class compiles, END of the order
    "cost_model": section_cost_model,
    # gnn compiles the windowed-GNN scan on the acceptance shape:
    # scan-class compile, END of the order beside cost_model
    "gnn": section_gnn,
    "fused": section_fused,
    "driver": section_driver,
}


def run_section_child(name: str) -> None:
    """Child mode: run ONE chip section in-process and print its JSON
    line — the FULL results dict, so auxiliary keys a section records
    next to its own (e.g. ingress_ab's `ingress_probes`) reach the
    orchestrator instead of vanishing with the child."""
    if name != "autotune":
        # measurement sections pin the STATIC configuration: the online
        # tuner (ops/autotune) changing dispatch knobs mid-rep would
        # make sweep/A-B rows measure a moving target. The `autotune`
        # section measures the tuner itself and re-enables it.
        os.environ["GS_AUTOTUNE"] = "0"
    import jax

    from gelly_streaming_tpu.utils import resilience

    results = {"backend": jax.default_backend(),
               "device": str(jax.devices()[0])}
    SECTIONS[name](results)
    # tier demotions during the section (core/driver._maybe_demote →
    # utils/resilience registry): a run that silently fell off the
    # device tier mid-measurement must be LABELED — the orchestrator
    # accumulates these into PERF.json's `degradations` section, so a
    # demoted chip run can never masquerade as a device-tier row
    events = resilience.demotion_events()
    if events:
        results["degradations"] = [dict(e, section=name)
                                   for e in events]
    print(json.dumps(results), flush=True)


def run_section_subprocess(name: str, timeout_s: int, env=None) -> dict:
    """Run one chip section in its own process group with a hard
    timeout. A wedged remote compile (the tunnel's known failure mode:
    one oversized program stalled it >30 min in round 2) then costs ONE
    section, not the whole profile."""
    from bench import run_json_child

    return run_json_child(
        [sys.executable, os.path.abspath(__file__), "--section", name],
        timeout_s, env=env)


def main():
    if len(sys.argv) >= 3 and sys.argv[1] == "--section":
        run_section_child(sys.argv[2])
        return
    if len(sys.argv) >= 5 and sys.argv[1] == "--probe":
        run_compile_probe_child(sys.argv[2], int(sys.argv[3]),
                                int(sys.argv[4]))
        return
    if len(sys.argv) >= 4 and sys.argv[1] == "--dense":
        run_dense_child(int(sys.argv[2]), sys.argv[3])
        return

    args = sys.argv[1:]
    unknown = [a for a in args if a not in SECTIONS and a != "sharded"]
    if unknown:
        sys.exit("unknown section(s) %s; valid: %s"
                 % (unknown, list(SECTIONS) + ["sharded"]))
    want = [s for s in list(SECTIONS) + ["sharded"]
            if not args or s in args]
    timeout_s = int(os.environ.get("GS_PROFILE_SECTION_TIMEOUT", "2400"))
    perf_path = os.path.join(REPO, "PERF.json")
    results = {}
    ok_sections = []
    wrote = [None]

    try:
        with open(perf_path) as f:
            prior = json.load(f)
    except (OSError, ValueError):
        prior = None

    def flush():
        # PERF.json drives the library's kernel auto-selection
        # (ops/triangles._load_tpu_perf), so a profiling RUN must never
        # degrade it:
        #  - no successful section yet -> write PERF.json.partial only;
        #  - same backend as the existing file -> merge this run's
        #    successful sections over it (a subset or interrupted run
        #    keeps the other sections' committed measurements);
        #  - different backend -> replace only when THIS run is the
        #    chip ('tpu'); a CPU-fallback run never overwrites a
        #    TPU-labeled file (it would silently deselect the measured
        #    kernels).
        backend = results.get("backend")
        # A failed section NEVER lands under its section key (library
        # consumers iterate section rows and would crash/mislead on an
        # {"error": ...} stub; _load_tpu_perf also filters these) —
        # it is recorded under <name>_error, keeping any prior
        # measurement. A same-backend prior file seeds the merge; any
        # other prior is ignored here (the usable check below decides
        # whether this run may replace it at all).
        merged = (dict(prior) if prior is not None
                  and prior.get("backend") == backend else {})
        for k, v in results.items():
            if isinstance(v, dict) and "error" in v:
                merged[k + "_error"] = v
            else:
                merged[k] = v
                merged.pop(k + "_error", None)
        replacing_other_backend = (
            prior is not None and prior.get("backend") != backend)
        usable = bool(ok_sections) and not (
            replacing_other_backend and prior.get("backend") == "tpu"
            and backend != "tpu")
        path = perf_path if usable else perf_path + ".partial"
        with open(path, "w") as f:
            json.dump(merged, f, indent=2)
        if ok_sections and backend:
            # per-backend archive: this backend's selections must keep
            # their committed rows even after the OTHER backend's
            # profile run takes over PERF.json
            # (ops/triangles._load_matching_perf falls back to it).
            # Seeded from the EXISTING archive so a subset run (e.g.
            # host_stream only) keeps the other archived sections.
            arch_path = os.path.join(REPO, "PERF_%s.json" % backend)
            try:
                with open(arch_path) as f:
                    arch = json.load(f)
                if arch.get("backend") != backend:
                    arch = {}
            except (OSError, ValueError):
                arch = {}
            arch.update(merged)
            # a section that succeeded THIS run clears its stale
            # failure stub from the archive too — the PERF.json merge
            # above already does; without this the archive keeps a
            # dead <name>_error beside the good rows forever
            for k in list(merged):
                if not k.endswith("_error"):
                    arch.pop(k + "_error", None)
            with open(arch_path, "w") as f:
                json.dump(arch, f, indent=2)
        wrote[0] = path

    chip_sections = [s for s in want if s != "sharded"]
    child_env = None
    if chip_sections:
        from bench import probe_backend

        platform = probe_backend()
        if platform is None:
            # Same CPU fallback as tools/scale_run.py: sections still
            # run (honestly labeled cpu), in a clean env with the
            # wedged PJRT plugin's registration stripped. The
            # kernel-inversion measurements (intersect/dense choices)
            # are exactly the kind of data a labeled CPU run records.
            print("no chip backend; sections fall back to clean-CPU env",
                  file=sys.stderr)
            from bench import clean_cpu_env

            child_env = clean_cpu_env()
            platform = "cpu"
        results["backend"] = platform
        flush()
    elif prior is not None:
        # sharded-only run: keep the existing file's chip identity
        results["backend"] = prior.get("backend")
        results["device"] = prior.get("device")
    for name in chip_sections:
        got = run_section_subprocess(name, timeout_s, env=child_env)
        # Trust the backend the CHILD measured on, not the pre-run
        # probe: a tunnel drop between probe and section would
        # otherwise commit CPU-fallback timings labeled as chip ones.
        child_backend = got.get("backend")
        if "error" not in got and child_backend != results["backend"]:
            got = {"error": "backend mismatch: probed %s, section ran "
                            "on %s" % (results["backend"], child_backend)}
        if got.get("device"):
            results.setdefault("device", got["device"])
        # a child that demoted tiers mid-measurement reports it even
        # when its section row also landed: accumulate across sections
        # (the `degradations` key in PERF.json is the honesty label —
        # update_perf_md/consumers can flag the affected rows)
        if got.get("degradations"):
            results.setdefault("degradations", []).extend(
                got["degradations"])
        results[name] = got.get(name, got if "error" in got else
                                {"error": "missing section key"})
        if "error" not in results[name]:
            ok_sections.append(name)
            # auxiliary keys a section recorded beside its own (e.g.
            # ingress_ab's `ingress_probes`) ride along into PERF.json
            for k, v in got.items():
                if k not in ("backend", "device", name, "degradations") \
                        and k not in SECTIONS:
                    results[k] = v
        print(json.dumps({name: results[name]}), flush=True)
        flush()
    if "sharded" in want:
        results["sharded"] = section_sharded(REPO)
        if "error" not in results["sharded"]:
            ok_sections.append("sharded")
            # hoist the table-mode comparison to the top level, where
            # parallel/sharded.resolve_table_mode reads it
            if "sharded_table" in results["sharded"]:
                results["sharded_table"] = results["sharded"].pop(
                    "sharded_table")
        print(json.dumps({"sharded": results["sharded"]}), flush=True)
        flush()
    print("wrote %s" % wrote[0], file=sys.stderr)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Lightweight schema check for PERF.json (and the per-backend
archives PERF_<backend>.json).

The committed evidence file drives the library's kernel
auto-selection (ops/triangles._load_matching_perf and friends) AND
the PERF.md renderer (tools/update_perf_md.py). A malformed section —
a dict where a row list belongs, a parity-true row without a speedup,
a degradation event missing its tiers — silently disables a selection
or crashes the unattended renderer at the END of a chip window, which
is exactly when raw output is lost. This validator is the cheap
tier-1 guard (tests/test_perf_tooling.py) that new profiler sections
can't break the contract unnoticed.

Usage: python tools/perf_schema.py [PERF.json ...]   (repo default)
Exit 0 = every file clean; errors list file:section:problem lines.

Forward-compatible by design: UNKNOWN top-level keys are allowed
(new sections land before the validator learns them); only the shape
of KNOWN sections is enforced.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# sections whose value must be a list of dict rows, with per-row
# REQUIRED keys (value None = key must exist, any type)
LIST_SECTIONS = {
    "intersect": (),          # dict OR list historically: checked below
    "window": ("edge_bucket",),
    "host_stream": ("edge_bucket", "parity"),
    "host_reduce": ("edge_bucket", "name", "parity"),
    "host_snapshot": ("edge_bucket", "parity"),
    "ingress_ab": ("probe", "parity"),
    "egress_ab": ("probe", "parity"),
    "resident_ab": ("probe", "parity"),
    # fused Pallas window megakernel A/B (tools/pallas_ab.py):
    # megakernel vs XLA scan-of-gathers, sha256 window parity vs the
    # host twins; resolve_pallas_window gates on these rows
    "pallas_ab": ("probe", "parity"),
    # multi-tenant cohort A/B (tools/tenancy_ab.py): N-tenant vmapped
    # dispatch vs N sequential single-tenant engines, per-tenant
    # sha256 parity. Probes: cohort_serving/cohort_batch (scan tier),
    # cohort_resident (donated stacked-carry super-batch tier, one row
    # per N — resolve_resident_cohort's adoption evidence),
    # cohort_pallas (tenant-axis Pallas megakernel; off-chip rows must
    # be interpret-marked, see _check_rows)
    "tenancy_ab": ("probe", "parity", "tenants"),
    # async-pump / sliding-pane A/B (tools/pump_ab.py). Probes:
    # serving_pump (GS_PUMP=async vs sync on a paced 8-tenant loopback
    # serve run, per-tenant sha256 parity, queue_wait/e2e p99
    # improvements), sliding_panes (pane-composed sliding reduce vs
    # the naive refold twin, bit-exact parity)
    "pump_ab": ("probe", "parity"),
    # windowed GNN A/B (tools/gnn_ab.py): engine vs numpy twin and
    # cohort vs N-sequential at sha256 feature-slab parity. Probes:
    # gnn_engine (device scan vs host twin), gnn_cohort (vmapped
    # N-tenant dispatch vs N sequential engines, one row per N),
    # gnn_pallas (fused kernel vs XLA round — resolve_gnn_pallas's
    # adoption evidence; off-chip rows must be interpret-marked, see
    # _check_rows)
    "gnn_ab": ("probe", "parity"),
    "autotune": ("engine", "parity"),
    "pipeline_stages": ("engine", "edge_bucket"),
    "chunk_deep": ("edge_bucket",),
    "compile_probe": ("program", "slots", "ok"),
    "compile_probe_scan": ("program", "slots", "ok"),
    # mesh_shape is REQUIRED (null = single-chip): a demoted mesh run
    # must carry its mesh provenance, so it can never masquerade as a
    # healthy sharded-tier row (utils/resilience.record_demotion is
    # the single producer and always stamps it, with shard_id beside)
    "degradations": ("from", "to", "window", "mesh_shape"),
    "ingress_probes": ("probe",),
    # flight-recorder summary rows (utils/telemetry.summary():
    # per-span latency aggregates a profiler/chaos run commits)
    "telemetry": ("span", "count"),
    # perf regression sentry rows (tools/bench_compare.py): one row
    # per (baseline row, field) whose current/baseline ratio fell
    # below tolerance — CI keys its red/green off this section
    "regressions": ("row", "field", "baseline", "current", "ratio"),
}

# dict-shaped sections with required keys (telemetry_meta predates
# this table and stays unvalidated for compatibility)
DICT_SECTIONS = {
    # metrics-plane overhead proof (tools/profile_kernels.py
    # section_metrics): armed-vs-disarmed wall ratio + digest parity
    # on the 524K/32768 row — the committed evidence for the
    # GS_METRICS ≤1.05× bar
    "metrics": ("engine", "parity", "overhead_ratio",
                "disarmed_edges_per_s", "armed_edges_per_s"),
    # program cost observatory (utils/costmodel, tools/
    # profile_kernels.py section_cost_model): per-program FLOPs/bytes
    # rows + the trace id of the committed attribution ledger
    # tools/explain_perf.py drills into
    "cost_model": ("programs", "parity", "edge_bucket", "trace",
                   "ledger"),
    # latency-plane overhead + reconciliation proof (utils/latency,
    # tools/profile_kernels.py section_latency): armed-vs-disarmed
    # wall ratio with digest parity on the 524K/32768 row, plus the
    # per-window waterfall conservation check (stages sum to e2e) —
    # the committed evidence for the GS_LATENCY ≤1.05× bar
    "latency": ("engine", "parity", "overhead_ratio",
                "disarmed_edges_per_s", "armed_edges_per_s",
                "reconciled_windows", "e2e_p99_s"),
    # admission-sanitizer overhead proof (utils/sanitize,
    # tools/profile_kernels.py section_sanitize): armed-vs-disarmed
    # wall ratio at digest parity on the 524K/32768 row, plus the
    # dlq_records/quarantines counters bench_compare checks
    # not-worse — the committed evidence for the GS_SANITIZE ≤1.02×
    # bar
    "sanitize": ("engine", "parity", "overhead_ratio",
                 "disarmed_edges_per_s", "armed_edges_per_s",
                 "dlq_records", "quarantines"),
    # provenance-ledger overhead + truth proof (utils/provenance,
    # tools/profile_kernels.py section_provenance): armed-vs-disarmed
    # wall ratio at digest parity on the 524K/32768 row, every armed
    # window's ledger digest asserted against the disarmed baseline
    # summary, plus the per-tenant attribution rows whose seconds
    # reconcile to the dispatch span — the committed evidence for the
    # GS_PROVENANCE ≤1.02× bar (ISSUE 20)
    "provenance": ("engine", "parity", "overhead_ratio",
                   "disarmed_edges_per_s", "armed_edges_per_s",
                   "records", "windows_verified", "attribution"),
    # windowed-GNN cost observatory rows (tools/profile_kernels.py
    # section_gnn / tools/gnn_ab.py --commit): the per-program
    # analytic cost rows for the MXU workload, with the stated
    # arithmetic intensity beside the measured throughput so PERF.md
    # shows whether the dense update moves the bound verdict off
    # `bytes` — plus digest parity vs the host twin on the same run
    "gnn": ("programs", "parity", "edge_bucket", "feature_dim"),
}

# per-row required keys of the cost_model section's `programs` list
# (flops/bytes may be null on a backend that doesn't report them, but
# the keys must exist so a consumer can tell "not reported" from a
# silently dropped capture)
_COST_PROGRAM_KEYS = ("program", "sig", "flops", "bytes_accessed",
                      "bound", "dispatches")

# A/B sections whose parity-true rows must claim a positive speedup
# (the adoption gates divide by it; rows_clear_bar rejects otherwise)
_AB_SECTIONS = ("ingress_ab", "egress_ab", "resident_ab",
                "tenancy_ab", "pallas_ab", "pump_ab", "gnn_ab")


def _check_rows(name: str, rows, errors) -> None:
    if not isinstance(rows, list):
        errors.append("%s: expected a list of rows, got %s"
                      % (name, type(rows).__name__))
        return
    for i, row in enumerate(rows):
        if not isinstance(row, dict):
            errors.append("%s[%d]: expected a dict row, got %s"
                          % (name, i, type(row).__name__))
            continue
        for key in LIST_SECTIONS.get(name, ()):
            if key not in row:
                errors.append("%s[%d]: missing required key %r"
                              % (name, i, key))
        if name in _AB_SECTIONS and row.get("parity") is True:
            sp = row.get("speedup")
            if not isinstance(sp, (int, float)) or sp <= 0:
                errors.append(
                    "%s[%d]: parity-true row needs a positive "
                    "'speedup' (got %r)" % (name, i, sp))
        if name == "tenancy_ab" \
                and row.get("probe") == "cohort_pallas" \
                and row.get("backend") != "tpu" \
                and row.get("interpret") is not True:
            # resolve_cohort_pallas ignores interpret rows for
            # adoption; an off-chip row missing the marker would
            # masquerade as chip speed evidence
            errors.append(
                "tenancy_ab[%d]: cohort_pallas row on backend %r "
                "must carry interpret: true" % (i, row.get("backend")))
        if name == "gnn_ab" \
                and row.get("probe") == "gnn_pallas" \
                and row.get("backend") != "tpu" \
                and row.get("interpret") is not True:
            # same contract for resolve_gnn_pallas's evidence rows
            errors.append(
                "gnn_ab[%d]: gnn_pallas row on backend %r must "
                "carry interpret: true" % (i, row.get("backend")))
        if name == "degradations":
            ms = row.get("mesh_shape")
            if ms is not None and not (
                    isinstance(ms, list)
                    and all(isinstance(x, int) for x in ms)):
                errors.append(
                    "degradations[%d]: 'mesh_shape' must be null or a "
                    "list of ints (got %r)" % (i, ms))
            sid = row.get("shard_id")
            if sid is not None and not isinstance(sid, int):
                errors.append(
                    "degradations[%d]: 'shard_id' must be null or an "
                    "int (got %r)" % (i, sid))


def validate(perf) -> list:
    """Error strings for one parsed PERF dict; empty = clean."""
    errors = []
    if not isinstance(perf, dict):
        return ["top level: expected a dict, got %s"
                % type(perf).__name__]
    if not isinstance(perf.get("backend"), str):
        errors.append("top level: 'backend' must be a string "
                      "(got %r)" % (perf.get("backend"),))
    for name, val in perf.items():
        if name.endswith("_error"):
            if not (isinstance(val, dict) and "error" in val):
                errors.append("%s: failed-section stub must be a dict "
                              "with an 'error' key" % name)
            continue
        if name == "intersect":
            # historically a single dict row; a list is also accepted
            if not isinstance(val, (dict, list)):
                errors.append("intersect: expected dict or list")
            continue
        if name in LIST_SECTIONS:
            _check_rows(name, val, errors)
        elif name in DICT_SECTIONS:
            if not isinstance(val, dict):
                errors.append("%s: expected a dict section, got %s"
                              % (name, type(val).__name__))
                continue
            for key in DICT_SECTIONS[name]:
                if key not in val:
                    errors.append("%s: missing required key %r"
                                  % (name, key))
            if name in ("cost_model", "gnn"):
                rows = val.get("programs")
                if not isinstance(rows, list):
                    if "programs" in val:
                        errors.append(
                            "%s: 'programs' must be a list of "
                            "rows, got %s" % (name, type(rows).__name__))
                else:
                    for i, row in enumerate(rows):
                        if not isinstance(row, dict):
                            errors.append(
                                "%s.programs[%d]: expected a "
                                "dict row, got %s"
                                % (name, i, type(row).__name__))
                            continue
                        for key in _COST_PROGRAM_KEYS:
                            if key not in row:
                                errors.append(
                                    "%s.programs[%d]: missing "
                                    "required key %r" % (name, i, key))
    return errors


def validate_capture(doc) -> list:
    """Error strings for one parsed BENCH_r*.json capture ({"n",
    "cmd", "rc", "tail", "parsed"} — the shape bench runs commit and
    tools/bench_compare.py reads); empty = clean."""
    errors = []
    if not isinstance(doc, dict):
        return ["top level: expected a dict capture, got %s"
                % type(doc).__name__]
    if not isinstance(doc.get("tail"), str):
        errors.append("capture: 'tail' must be the bench stdout tail "
                      "string (got %r)" % type(doc.get("tail")).__name__)
    if "rc" in doc and not isinstance(doc["rc"], int):
        errors.append("capture: 'rc' must be an int exit status")
    parsed = doc.get("parsed")
    if parsed is not None and not isinstance(parsed, dict):
        errors.append("capture: 'parsed' must be null or the last "
                      "metric row dict")
    return errors


def is_capture(doc) -> bool:
    """True for the BENCH_r*.json capture shape (tail + cmd/rc),
    which main() routes to validate_capture instead of validate."""
    return isinstance(doc, dict) and "tail" in doc \
        and ("cmd" in doc or "rc" in doc)


# per-leg required keys of the chaos soak summary
# (tools/chaos_run.py; every committed logs/CHAOS_*.json). Legs are
# optional (older soaks predate newer legs) but a PRESENT leg must
# carry its keys — a soak that "passed" without its parity flag is
# exactly the silent-drift CI must refuse.
_CHAOS_LEGS = {
    "driver_leg": ("parity", "faults_fired", "resumed_from_window"),
    "engine_leg": ("parity", "faults_fired", "killed_at_call"),
    "resident_leg": ("parity", "faults_fired"),
    "tenancy_leg": ("parity", "faults_fired", "resumed"),
    # the durable-serving drill (ISSUE 12): kill→WAL-replay parity,
    # torn tail falling back one record, slow-client shed, and the
    # graceful SIGTERM drain (subprocess exits 0, sealed journal,
    # drain digest ≡ keep-running digest)
    "serve_leg": ("parity", "kill", "torn_tail", "slow_client",
                  "drain"),
    # the latency-plane drill (latency ISSUE): kill→WAL-replay
    # recovery must preserve admission timestamps — replayed windows
    # report honest, larger latency, never reset-to-zero — at armed
    # summaries digest-identical to the fault-free oracle
    "latency_leg": ("parity", "preserved", "replayed_windows"),
    # the poison-input drill (ISSUE 15): a hostile tenant flooding
    # garbage is sanitized (every rejected edge recoverable from the
    # dead-letter journal) and quarantined by the cohort bulkhead
    # while the healthy tenants stay bit-identical; the serve
    # subprocess under the flood must still drain rc=0
    "poison_leg": ("parity", "quarantined", "dlq_recovered", "drain"),
    # the async-pump drill (ISSUE 18): SIGKILL a GS_PUMP=async serve
    # subprocess mid-pump, WAL-replay into a fresh async server, and
    # the union of pre-kill deliveries + replayed windows must be
    # digest-identical to the sync fault-free oracle — with at least
    # one ingest batch accepted while a dispatch was in flight
    # (overlap_feeds > 0: the leg proves the overlap path, not a
    # quietly serialized pump)
    "pump_leg": ("parity", "faults_fired", "overlap_feeds"),
    # the windowed-GNN drill (ISSUE 19): fatal kill mid-stream on a
    # checkpoint+WAL-armed GnnSummaryEngine, resume into a fresh
    # engine, and the final feature slab + combined summaries must be
    # digest-identical to the fault-free oracle (weights restored
    # from the checkpoint's gnn section, never re-seeded)
    "gnn_leg": ("parity", "faults_fired", "resumed_from_window"),
    # the provenance-ledger drill (ISSUE 20): a fully armed cohort
    # (provenance + WAL + checkpoints) killed fatally mid-dispatch,
    # recovered, and the re-emitted provenance records — including
    # the at-least-once duplicates for replayed windows — must be
    # byte-identical to the fault-free oracle's ledger (a crash can
    # never fork the audit trail)
    "provenance_leg": ("parity", "faults_fired", "records",
                       "re_emitted"),
}


def is_chaos(doc) -> bool:
    """True for the tools/chaos_run.py soak-summary shape, which
    main() routes to validate_chaos."""
    return isinstance(doc, dict) and "fault_classes_fired" in doc


def validate_chaos(doc) -> list:
    """Error strings for one parsed logs/CHAOS_*.json soak summary;
    empty = clean."""
    errors = []
    if not isinstance(doc, dict):
        return ["top level: expected a dict soak summary"]
    if doc.get("parity") is not True:
        errors.append("chaos: top-level 'parity' must be true — a "
                      "diverged soak must never be committed")
    if not isinstance(doc.get("fault_classes_fired"), list):
        errors.append("chaos: 'fault_classes_fired' must be a list")
    for leg, keys in _CHAOS_LEGS.items():
        val = doc.get(leg)
        if val is None:
            continue  # legs are additive across soak generations
        if not isinstance(val, dict):
            errors.append("%s: expected a dict leg, got %s"
                          % (leg, type(val).__name__))
            continue
        for key in keys:
            if key not in val:
                errors.append("%s: missing required key %r"
                              % (leg, key))
        if val.get("parity") is not True:
            errors.append("%s: leg 'parity' must be true" % leg)
    for leg_name in ("serve_leg", "poison_leg"):
        leg = doc.get(leg_name)
        if not isinstance(leg, dict):
            continue
        drain = leg.get("drain")
        if isinstance(drain, dict):
            for key in ("rc", "sealed", "digest_match"):
                if key not in drain:
                    errors.append("%s.drain: missing required "
                                  "key %r" % (leg_name, key))
            if drain.get("rc") != 0:
                errors.append("%s.drain: SIGTERM drain must "
                              "exit 0 (got %r)"
                              % (leg_name, drain.get("rc")))
        elif drain is not None:
            errors.append("%s.drain: expected a dict" % leg_name)
    return errors


def main(paths=None) -> int:
    paths = paths or [os.path.join(REPO, "PERF.json")]
    rc = 0
    for path in paths:
        try:
            with open(path) as f:
                perf = json.load(f)
        except (OSError, ValueError) as e:
            print("%s: unreadable (%s)" % (path, e))
            rc = 1
            continue
        errors = (validate_capture(perf) if is_capture(perf)
                  else validate_chaos(perf) if is_chaos(perf)
                  else validate(perf))
        if errors:
            rc = 1
            for e in errors:
                print("%s: %s" % (os.path.basename(path), e))
        else:
            print("%s: ok (%d top-level keys)"
                  % (os.path.basename(path), len(perf)))
    return rc


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:] or None))

#!/usr/bin/env python
"""Egress A/B: does delta-compacted d2h egress (ops/delta_egress.py)
beat full-vector snapshot shipping end-to-end — with EXACT parity?

Two probes, each a JSON row:

  driver_ab — StreamingAnalyticsDriver over the canonical 524K/32768
              row (bench.make_stream), scan tier pinned, full vs
              delta egress; bit parity asserted window-by-window
              (sha256 over every snapshot field INCLUDING the delta
              tuples) before any speedup is claimed.
  reduce_ab — WindowedEdgeReduce monoid device tier at a
              vbp >> eb shape (where the touched-cell wire actually
              shrinks bytes), full vs delta; cells AND counts
              bit-identical per window.

Timing is median-of-3 with min/max dispersion committed in the row
(the ingress A/B's 1.13x/1.02x flip-flop taught us a single run is
load noise, not evidence). GS_AUTOTUNE is pinned OFF inside the
probes so the egress lever is measured in isolation.

The committed `egress_ab` rows are what ops/delta_egress.
resolve_egress gates on: parity true AND >=5% on EVERY row, or
full-vector stands. Run after the evidence queue (tools/tpu_queue.sh);
commit policy identical to tools/ingress_ab.py (PERF.json only when
backend-matched, PERF_<backend>.json always).
"""

import hashlib
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from bench import make_stream  # noqa: E402


def timed_stats(fn, reps=3, warmup=1):
    """median/min/max wall seconds of fn() — the dispersion trio every
    A/B row commits so the adoption bar is never decided by one
    load-noisy draw."""
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return (float(np.median(ts)), float(np.min(ts)), float(np.max(ts)))


def _dispersion(row: dict, prefix: str, stats) -> None:
    med, lo, hi = stats
    row[prefix + "_s"] = round(med, 4)
    row[prefix + "_s_min"] = round(lo, 4)
    row[prefix + "_s_max"] = round(hi, 4)


def _digest_windows(results) -> list:
    out = []
    for r in results:
        h = hashlib.sha256()
        for a in (r.vertex_ids, r.degrees, r.cc_labels,
                  r.bipartite_odd):
            if a is not None:
                h.update(np.ascontiguousarray(a).tobytes())
        for t in (r.delta_degrees, r.delta_cc, r.delta_bipartite):
            if t is not None:
                h.update(np.ascontiguousarray(t[0]).tobytes())
                h.update(np.ascontiguousarray(t[1]).tobytes())
        out.append((int(r.window_start), int(r.num_edges),
                    None if r.triangles is None else int(r.triangles),
                    h.hexdigest()[:16]))
    return out


def driver_ab(jax, num_edges, results):
    from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
    from gelly_streaming_tpu.ops import delta_egress

    eb, vb = 32768, 65536
    src, dst = make_stream(num_edges, vb)

    def build(egress):
        return StreamingAnalyticsDriver(
            window_ms=0, edge_bucket=eb, vertex_bucket=vb,
            snapshot_tier="scan", egress=egress, emit_deltas=True)

    drivers = {e: build(e) for e in ("full", "delta")}
    digests = {}
    for e, drv in drivers.items():
        digests[e] = _digest_windows(drv.run_arrays(src, dst))  # warm
        drv.reset()
    parity = digests["full"] == digests["delta"]

    stats = {}
    for e, drv in drivers.items():
        def run(drv=drv):
            drv.reset()
            drv.run_arrays(src, dst)

        stats[e] = timed_stats(run, reps=3, warmup=0)

    row = {
        "probe": "driver_ab",
        "backend": jax.default_backend(),
        "num_edges": len(src), "eb": eb, "vb": vb,
        "cap": delta_egress.egress_cap(eb, vb),
        "full_edges_per_s": round(len(src) / stats["full"][0]),
        "delta_edges_per_s": round(len(src) / stats["delta"][0]),
        "parity": bool(parity),
    }
    _dispersion(row, "full", stats["full"])
    _dispersion(row, "delta", stats["delta"])
    if parity:
        row["speedup"] = round(stats["full"][0] / stats["delta"][0], 3)
        # worst/best-case ratio across the dispersion envelope: the
        # adoption bar should clear even the pessimistic pairing
        row["speedup_worst"] = round(
            stats["full"][1] / stats["delta"][2], 3)
        row["speedup_best"] = round(
            stats["full"][2] / stats["delta"][1], 3)
    else:
        print("PARITY FAILURE between egress forms (driver)",
              file=sys.stderr)
    results.append(row)
    print(json.dumps(row), flush=True)


def reduce_ab(jax, num_edges, results):
    from gelly_streaming_tpu.ops.windowed_reduce import (
        WindowedEdgeReduce)

    eb, vb = 4096, 65536  # vbp >> eb: the shape the wire shrinks
    src, dst = make_stream(num_edges, vb, seed=11)
    src64 = src.astype(np.int64)
    dst64 = dst.astype(np.int64)
    val = (1 + (src + 3 * dst) % 97).astype(np.int64)

    engines = {e: WindowedEdgeReduce(
        vertex_bucket=vb, edge_bucket=eb, name="sum",
        direction="out", egress=e) for e in ("full", "delta")}
    rows = {e: eng._device_process_stream(src64, dst64, val)
            for e, eng in engines.items()}  # warm + parity material
    parity = len(rows["full"]) == len(rows["delta"]) and all(
        np.array_equal(np.asarray(c0), np.asarray(c1))
        and np.array_equal(np.asarray(n0), np.asarray(n1))
        for (c0, n0), (c1, n1) in zip(rows["full"], rows["delta"]))

    stats = {e: timed_stats(
        lambda eng=eng: eng._device_process_stream(src64, dst64, val),
        reps=3, warmup=0) for e, eng in engines.items()}

    row = {
        "probe": "reduce_ab",
        "backend": jax.default_backend(),
        "num_edges": len(src), "eb": eb, "vb": vb, "name": "sum",
        "full_edges_per_s": round(len(src) / stats["full"][0]),
        "delta_edges_per_s": round(len(src) / stats["delta"][0]),
        "parity": bool(parity),
    }
    _dispersion(row, "full", stats["full"])
    _dispersion(row, "delta", stats["delta"])
    if parity:
        row["speedup"] = round(stats["full"][0] / stats["delta"][0], 3)
        row["speedup_worst"] = round(
            stats["full"][1] / stats["delta"][2], 3)
        row["speedup_best"] = round(
            stats["full"][2] / stats["delta"][1], 3)
    else:
        print("PARITY FAILURE between egress forms (reduce)",
              file=sys.stderr)
    results.append(row)
    print(json.dumps(row), flush=True)


PROBE_NAMES = ("driver_ab", "reduce_ab")


def commit_results(results, backend: str) -> None:
    """Merge this run's `egress_ab` rows into the committed evidence —
    the same policy as tools/ingress_ab.py: PERF.json only when its
    backend label matches the live backend, the per-backend archive
    PERF_<backend>.json always."""
    targets = ((os.path.join(REPO, "PERF.json"), True),
               (os.path.join(REPO, "PERF_%s.json" % backend), False))
    for path, need_match in targets:
        try:
            with open(path) as f:
                cur = json.load(f)
        except (OSError, ValueError):
            cur = {}
        if need_match and cur.get("backend") != backend:
            print("not committing to %s: file backend %r != live %r"
                  % (os.path.basename(path), cur.get("backend"),
                     backend), file=sys.stderr)
            continue
        cur.setdefault("backend", backend)
        cur["egress_ab"] = results
        with open(path, "w") as f:
            json.dump(cur, f, indent=2)
        print("committed %s row(s) to %s"
              % (len(results), os.path.basename(path)), flush=True)


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("probes", nargs="*",
                    help="subset of %s to run (default: all)"
                         % (PROBE_NAMES,))
    ap.add_argument("--edges", type=int,
                    default=int(os.environ.get("GS_AB_EDGES", 524_288)))
    ap.add_argument("--commit", action="store_true",
                    help="merge rows into PERF.json (backend-matched) "
                         "and PERF_<backend>.json")
    args = ap.parse_args()
    bad = [p for p in args.probes if p not in PROBE_NAMES]
    if bad:
        ap.error("unknown probe(s) %s; valid: %s"
                 % (bad, list(PROBE_NAMES)))
    want = args.probes or list(PROBE_NAMES)

    # measure the egress lever in isolation: the online tuner changing
    # dispatch knobs between reps would be noise here
    os.environ["GS_AUTOTUNE"] = "0"

    import jax

    results = []
    if "driver_ab" in want:
        driver_ab(jax, args.edges, results)
    if "reduce_ab" in want:
        reduce_ab(jax, args.edges, results)
    out = os.path.join(REPO, "logs",
                       "egress_ab_%s.json" % jax.default_backend())
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote %s" % out, flush=True)
    if args.commit:
        commit_results(results, jax.default_backend())


if __name__ == "__main__":
    main()

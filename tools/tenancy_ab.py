#!/usr/bin/env python
"""Multi-tenant cohort A/B: does ONE vmapped cohort dispatch over N
streams (core/tenancy.TenantCohort) beat N sequential single-tenant
engines — with EXACT per-tenant parity?

Four probes, each a JSON row:

  cohort_serving — the serving shape ("millions of users = thousands
              of small streams"): N tenants fed window by window in
              arrival order, both sides pumping every round. The
              cohort folds the round's N windows in ONE vmapped
              dispatch; the sequential oracle runs N StreamSummary-
              Engine.process() calls of one window each — the
              per-dispatch wall the ROADMAP names, paid N times per
              round. Per-tenant sha256 over the summary stream must
              match the oracle exactly before any speedup is claimed.
  cohort_batch — the drain shape: deep queues, the cohort catching up
              at its windows-per-dispatch ceiling vs each sequential
              engine folding its whole stream at the chunked scan's
              normal 64-window dispatches. This is the UNFAVORABLE
              baseline for the cohort (the oracle amortizes its own
              dispatches) — committed beside the serving row so the
              evidence shows both economics.
  cohort_resident — the resident-cohort tier (GS_COHORT_RESIDENT=on):
              the donated [N, ...] stacked-carry super-batch program
              vs the same N-sequential per-window oracle, one row per
              N in {1, 3, 8} at the serving shape. These rows are the
              tier's adoption evidence (resident_engine.
              resolve_resident_cohort reads them through the
              rows_clear_bar gate); the N=1 row is committed precisely
              BECAUSE its speedup is ~1.0 — it keeps auto adoption
              honest on backends where one tenant gains nothing.
  cohort_pallas — the tenant-axis Pallas megakernel
              (GS_COHORT_PALLAS=on). Off-TPU this runs in interpret
              mode and the row carries `interpret: true`;
              pallas_window.resolve_cohort_pallas ignores interpret
              rows for adoption, so these rows are PARITY evidence
              (per-tenant sha256 vs the oracle), not speed evidence.

Timing is median-of-3 with min/max dispersion in the row (the ingress
A/B's flip-flop taught us a single draw is load noise). GS_AUTOTUNE
is pinned OFF inside the probes so the cross-tenant batching lever is
measured in isolation; GS_TENANT_TPD=0 then dispatches all ready
tenants in one slab.

The committed `tenancy_ab` rows are the cohort's adoption evidence
(the acceptance bar: serving-row speedup ≥1.5x at N=8 with exact
parity; if the bar is missed the rows are committed anyway and the
cohort path stays an explicit opt-in — report honestly, like the
resident tier). Commit policy identical to tools/resident_ab.py.

`--smoke` is the CI parity gate (tools/ci_check.sh): a 1-tenant
cohort must produce the BYTE-IDENTICAL summary digest of a single
StreamSummaryEngine fed the same stream — the cohort path can never
silently drift from the single-stream semantics. `--resident-smoke`
is the resident-tier twin: a 2-tenant cohort pinned to
GS_COHORT_RESIDENT=on must match two single-stream engines AND must
have actually taken the resident path (resident_dispatches > 0) — a
silent decline to the scan tier fails the gate rather than passing
vacuously.
"""

import hashlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from bench import make_stream  # noqa: E402
from tools.egress_ab import _dispersion, timed_stats  # noqa: E402


def digest_summaries(summaries) -> str:
    """sha256 over the summary-dict stream (every field, in window
    order) — the per-tenant parity identity."""
    h = hashlib.sha256()
    for s in summaries:
        h.update(json.dumps(s, sort_keys=True).encode())
    return h.hexdigest()[:16]


def make_tenant_streams(n_tenants: int, windows: int, eb: int,
                        vb: int, ragged: bool = True):
    """One deterministic power-law stream per tenant; ragged lengths
    (a short partial tail on some tenants) exercise the right-padding
    path the slab exists for."""
    streams = {}
    for i in range(n_tenants):
        n = windows * eb
        if ragged and i % 3 == 2:
            n -= eb // 3  # partial final window
        s, d = make_stream(n, vb, seed=100 + i)
        streams["t%02d" % i] = (s.astype(np.int32), d.astype(np.int32))
    return streams


def sequential_oracle(streams, eb, vb, per_window: bool):
    """N single-tenant engines. per_window=True replays the serving
    shape (one process() call per arrived window, round-robin);
    False folds each stream in one chunked call."""
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    out = {}
    engines = {tid: StreamSummaryEngine(edge_bucket=eb,
                                        vertex_bucket=vb)
               for tid in streams}
    if not per_window:
        for tid, (s, d) in streams.items():
            out[tid] = engines[tid].process(s, d)
        return out
    out = {tid: [] for tid in streams}
    cursors = {tid: 0 for tid in streams}
    live = True
    while live:
        live = False
        for tid, (s, d) in streams.items():
            c = cursors[tid]
            if c >= len(s):
                continue
            hi = min(c + eb, len(s))
            # a trailing partial window is the stream's FINAL call —
            # exactly the count-based tumbling contract
            out[tid].extend(engines[tid].process(s[c:hi], d[c:hi]))
            cursors[tid] = hi
            live = True
    return out


_ORACLE_CACHE = {}


def oracle_cached(streams, eb, vb, per_window: bool):
    """Per-(N, shape) memo of the N-sequential oracle within one run:
    cohort_serving, cohort_resident and cohort_pallas all compare
    against the SAME oracle at the same (N, eb, vb) shape, so compute
    it once. The timed reps still recompute it live (that's the
    baseline being measured) and _probe asserts the recomputation's
    per-tenant digests are identical to the cached ones — the cache
    can never mask oracle drift."""
    key = (tuple(sorted(streams)), eb, vb, per_window,
           sum(len(s) for s, _d in streams.values()))
    hit = _ORACLE_CACHE.get(key)
    if hit is None:
        hit = sequential_oracle(streams, eb, vb, per_window)
        _ORACLE_CACHE[key] = hit
    return hit


class scoped_env:
    """Pin GS_* knobs for one probe side and restore afterwards,
    resetting the memoised cohort-tier resolvers so the pin is seen
    (resolve_* caches the auto decision per process)."""

    def __init__(self, **pins):
        self.pins = pins
        self._old = {}

    def _reset(self):
        from gelly_streaming_tpu.ops import pallas_window
        from gelly_streaming_tpu.ops import resident_engine
        resident_engine._reset_resident_cohort()
        pallas_window._reset_pallas_window()

    def __enter__(self):
        for k, v in self.pins.items():
            self._old[k] = os.environ.get(k)
            os.environ[k] = v
        self._reset()
        return self

    def __exit__(self, *exc):
        for k, old in self._old.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        self._reset()
        return False


def cohort_run(streams, eb, vb, per_window: bool):
    """The cohort side: admit everyone, feed in arrival order, pump.
    per_window=True feeds one window per tenant per round (the
    serving shape — every round is one vmapped dispatch); False
    preloads the queues and lets pump() catch up at its
    windows-per-dispatch ceiling."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort

    co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    for tid in streams:
        co.admit(tid)
    out = {tid: [] for tid in streams}
    cursors = {tid: 0 for tid in streams}
    live = True
    while live:
        live = False
        for tid, (s, d) in streams.items():
            c = cursors[tid]
            if c >= len(s):
                continue
            hi = min(c + eb, len(s)) if per_window \
                else min(c + 4 * eb, len(s))
            co.feed(tid, s[c:hi], d[c:hi])
            cursors[tid] = hi
            live = True
        for tid, res in co.pump().items():
            out[tid].extend(res)
    for tid in streams:
        out[tid].extend(co.close(tid))
    return out


def _probe(name: str, jax, streams, eb, vb, per_window: bool,
           results: list, pins=None, extra=None) -> None:
    """One probe row. `pins` are GS_* knobs applied around the COHORT
    side only (the oracle is always the plain N-sequential baseline);
    `extra` keys are merged into the row verbatim."""
    total_edges = sum(len(s) for s, _d in streams.values())
    want = oracle_cached(streams, eb, vb, per_window)
    want_digests = {t: digest_summaries(want[t]) for t in streams}
    with scoped_env(**(pins or {})):
        got = cohort_run(streams, eb, vb, per_window)
        coh = timed_stats(
            lambda: cohort_run(streams, eb, vb, per_window),
            reps=3, warmup=0)
    parity = all(digest_summaries(got[t]) == want_digests[t]
                 for t in streams)

    relive = {}
    seq = timed_stats(
        lambda: relive.update(
            out=sequential_oracle(streams, eb, vb, per_window)),
        reps=3, warmup=0)
    # the oracle-cache identity: a live recomputation (the timed
    # baseline) must reproduce the cached oracle's digests exactly
    assert all(digest_summaries(relive["out"][t]) == want_digests[t]
               for t in streams), \
        "oracle cache drift: recomputed digests differ (%s)" % name

    row = {
        "probe": name,
        "backend": jax.default_backend(),
        "tenants": len(streams),
        "eb": eb, "vb": vb,
        "num_edges": total_edges,
        "windows": sum(-(-len(s) // eb)
                       for s, _d in streams.values()),
        "tenant_edges_per_s": round(total_edges / coh[0]),
        "sequential_edges_per_s": round(total_edges / seq[0]),
        "parity": bool(parity),
        "tenant_digests": {t: digest_summaries(got[t])
                           for t in sorted(streams)},
    }
    row.update(extra or {})
    _dispersion(row, "cohort", coh)
    _dispersion(row, "sequential", seq)
    if parity:
        row["speedup"] = round(seq[0] / coh[0], 3)
        row["speedup_worst"] = round(seq[1] / coh[2], 3)
        row["speedup_best"] = round(seq[2] / coh[1], 3)
    else:
        bad = [t for t in streams
               if digest_summaries(got[t]) != want_digests[t]]
        print("PARITY FAILURE (%s): tenants %s diverged from the "
              "sequential oracle" % (name, bad), file=sys.stderr)
    results.append(row)
    print(json.dumps(row), flush=True)


def smoke() -> int:
    """The ci_check gate: a 1-tenant cohort's digest must be
    byte-identical to a single StreamSummaryEngine's on the same
    stream (full + partial windows), in seconds not minutes."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    eb, vb = 512, 1024
    n = 5 * eb + eb // 4  # 5 full windows + a partial tail
    s, d = make_stream(n, vb, seed=11)
    s, d = s.astype(np.int32), d.astype(np.int32)
    want = StreamSummaryEngine(edge_bucket=eb,
                               vertex_bucket=vb).process(s, d)
    co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    co.admit("solo")
    got = []
    for lo in range(0, n, 2 * eb):
        co.feed("solo", s[lo:lo + 2 * eb], d[lo:lo + 2 * eb])
        got.extend(co.pump().get("solo", []))
    got.extend(co.close("solo"))
    if digest_summaries(got) != digest_summaries(want) \
            or len(got) != len(want):
        print("tenancy smoke FAILED: 1-tenant cohort digest %s != "
              "single-stream digest %s (%d vs %d windows)"
              % (digest_summaries(got), digest_summaries(want),
                 len(got), len(want)), file=sys.stderr)
        return 1
    print("tenancy smoke ok: 1-tenant cohort ≡ single stream (%s, "
          "%d windows)" % (digest_summaries(got), len(got)),
          flush=True)
    return 0


def resident_smoke() -> int:
    """The ci_check resident gate: a 2-tenant cohort pinned to the
    resident tier must (a) match two single-stream engines per-tenant
    byte-for-byte AND (b) have actually dispatched through the
    resident super-batch program — a silent decline to the scan tier
    (resident_dispatches == 0) FAILS instead of passing vacuously."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    eb, vb = 512, 1024
    streams = make_tenant_streams(2, 5, eb, vb, ragged=True)
    want = {tid: StreamSummaryEngine(edge_bucket=eb,
                                     vertex_bucket=vb).process(s, d)
            for tid, (s, d) in streams.items()}
    with scoped_env(GS_COHORT_RESIDENT="on"):
        co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        for tid in streams:
            co.admit(tid)
        got = {tid: [] for tid in streams}
        cursors = {tid: 0 for tid in streams}
        live = True
        while live:
            live = False
            for tid, (s, d) in streams.items():
                c = cursors[tid]
                if c >= len(s):
                    continue
                hi = min(c + eb, len(s))
                co.feed(tid, s[c:hi], d[c:hi])
                cursors[tid] = hi
                live = True
            for tid, res in co.pump().items():
                got[tid].extend(res)
        for tid in streams:
            got[tid].extend(co.close(tid))
        dispatches = co.resident_dispatches
    if dispatches == 0:
        print("resident smoke FAILED: GS_COHORT_RESIDENT=on but the "
              "cohort never took the resident super-batch path "
              "(resident_dispatches=0) — silent decline",
              file=sys.stderr)
        return 1
    bad = [t for t in streams
           if digest_summaries(got[t]) != digest_summaries(want[t])]
    if bad:
        print("resident smoke FAILED: tenants %s diverged from the "
              "single-stream engines" % bad, file=sys.stderr)
        return 1
    print("resident smoke ok: 2-tenant resident cohort ≡ single "
          "streams (%d resident dispatches)" % dispatches, flush=True)
    return 0


PROBE_NAMES = ("cohort_serving", "cohort_batch", "cohort_resident",
               "cohort_pallas")


def commit_results(results, backend: str) -> None:
    """Merge this run's `tenancy_ab` rows into the committed evidence
    — the same policy as tools/resident_ab.py: PERF.json only when
    its backend label matches the live backend, the per-backend
    archive PERF_<backend>.json always. Merge is BY PROBE: only the
    probes this run produced are replaced, so a cohort_resident-only
    run can't evict the committed cohort_serving/cohort_batch rows."""
    ran = {r["probe"] for r in results}
    targets = ((os.path.join(REPO, "PERF.json"), True),
               (os.path.join(REPO, "PERF_%s.json" % backend), False))
    for path, need_match in targets:
        try:
            with open(path) as f:
                cur = json.load(f)
        except (OSError, ValueError):
            cur = {}
        if need_match and cur.get("backend") != backend:
            print("not committing to %s: file backend %r != live %r"
                  % (os.path.basename(path), cur.get("backend"),
                     backend), file=sys.stderr)
            continue
        cur.setdefault("backend", backend)
        kept = [r for r in cur.get("tenancy_ab", [])
                if r.get("probe") not in ran]
        cur["tenancy_ab"] = kept + results
        with open(path, "w") as f:
            json.dump(cur, f, indent=2)
        print("committed %s row(s) to %s (%d prior row(s) kept)"
              % (len(results), os.path.basename(path), len(kept)),
              flush=True)


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("probes", nargs="*",
                    help="subset of %s to run (default: all)"
                         % (PROBE_NAMES,))
    ap.add_argument("--tenants", type=int,
                    default=int(os.environ.get("GS_AB_TENANTS", 8)))
    ap.add_argument("--windows", type=int,
                    default=int(os.environ.get("GS_AB_WINDOWS", 16)),
                    help="windows per tenant")
    ap.add_argument("--eb", type=int,
                    default=int(os.environ.get("GS_AB_EB", 512)))
    ap.add_argument("--vb", type=int,
                    default=int(os.environ.get("GS_AB_VB", 1024)))
    ap.add_argument("--smoke", action="store_true",
                    help="CI parity gate only: 1-tenant cohort must "
                         "equal the single-stream digest")
    ap.add_argument("--resident-smoke", action="store_true",
                    help="CI resident gate: 2-tenant cohort pinned "
                         "GS_COHORT_RESIDENT=on must equal the "
                         "single-stream digests AND have taken the "
                         "resident path")
    ap.add_argument("--commit", action="store_true",
                    help="merge rows into PERF.json (backend-matched) "
                         "and PERF_<backend>.json")
    args = ap.parse_args()
    bad = [p for p in args.probes if p not in PROBE_NAMES]
    if bad:
        ap.error("unknown probe(s) %s; valid: %s"
                 % (bad, list(PROBE_NAMES)))
    want = args.probes or list(PROBE_NAMES)

    # measure the cross-tenant batching lever in isolation: the online
    # tuner changing dispatch knobs between reps would be noise here
    os.environ["GS_AUTOTUNE"] = "0"

    if args.smoke:
        sys.exit(smoke())
    if args.resident_smoke:
        sys.exit(resident_smoke())

    import jax

    streams = make_tenant_streams(args.tenants, args.windows,
                                  args.eb, args.vb)
    results = []
    if "cohort_serving" in want:
        _probe("cohort_serving", jax, streams, args.eb, args.vb,
               True, results)
    if "cohort_batch" in want:
        _probe("cohort_batch", jax, streams, args.eb, args.vb,
               False, results)
    if "cohort_resident" in want:
        # one row per cohort size: N=1 (the honest no-gain floor),
        # N=3 (mixed), N=args.tenants (the serving acceptance shape)
        for n in sorted({1, 3, args.tenants}):
            sub = make_tenant_streams(n, args.windows, args.eb,
                                      args.vb)
            _probe("cohort_resident", jax, sub, args.eb, args.vb,
                   True, results,
                   pins={"GS_COHORT_RESIDENT": "on"})
    if "cohort_pallas" in want:
        on_tpu = jax.default_backend() == "tpu"
        for n in sorted({1, 3, args.tenants}):
            sub = make_tenant_streams(n, args.windows, args.eb,
                                      args.vb)
            _probe("cohort_pallas", jax, sub, args.eb, args.vb,
                   True, results,
                   pins={"GS_COHORT_PALLAS": "on"},
                   extra={} if on_tpu else {"interpret": True})
    out = os.path.join(REPO, "logs",
                       "tenancy_ab_%s.json" % jax.default_backend())
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote %s" % out, flush=True)
    if args.commit:
        commit_results(results, jax.default_backend())


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Multi-tenant cohort A/B: does ONE vmapped cohort dispatch over N
streams (core/tenancy.TenantCohort) beat N sequential single-tenant
engines — with EXACT per-tenant parity?

Two probes, each a JSON row:

  cohort_serving — the serving shape ("millions of users = thousands
              of small streams"): N tenants fed window by window in
              arrival order, both sides pumping every round. The
              cohort folds the round's N windows in ONE vmapped
              dispatch; the sequential oracle runs N StreamSummary-
              Engine.process() calls of one window each — the
              per-dispatch wall the ROADMAP names, paid N times per
              round. Per-tenant sha256 over the summary stream must
              match the oracle exactly before any speedup is claimed.
  cohort_batch — the drain shape: deep queues, the cohort catching up
              at its windows-per-dispatch ceiling vs each sequential
              engine folding its whole stream at the chunked scan's
              normal 64-window dispatches. This is the UNFAVORABLE
              baseline for the cohort (the oracle amortizes its own
              dispatches) — committed beside the serving row so the
              evidence shows both economics.

Timing is median-of-3 with min/max dispersion in the row (the ingress
A/B's flip-flop taught us a single draw is load noise). GS_AUTOTUNE
is pinned OFF inside the probes so the cross-tenant batching lever is
measured in isolation; GS_TENANT_TPD=0 then dispatches all ready
tenants in one slab.

The committed `tenancy_ab` rows are the cohort's adoption evidence
(the acceptance bar: serving-row speedup ≥1.5x at N=8 with exact
parity; if the bar is missed the rows are committed anyway and the
cohort path stays an explicit opt-in — report honestly, like the
resident tier). Commit policy identical to tools/resident_ab.py.

`--smoke` is the CI parity gate (tools/ci_check.sh): a 1-tenant
cohort must produce the BYTE-IDENTICAL summary digest of a single
StreamSummaryEngine fed the same stream — the cohort path can never
silently drift from the single-stream semantics.
"""

import hashlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from bench import make_stream  # noqa: E402
from tools.egress_ab import _dispersion, timed_stats  # noqa: E402


def digest_summaries(summaries) -> str:
    """sha256 over the summary-dict stream (every field, in window
    order) — the per-tenant parity identity."""
    h = hashlib.sha256()
    for s in summaries:
        h.update(json.dumps(s, sort_keys=True).encode())
    return h.hexdigest()[:16]


def make_tenant_streams(n_tenants: int, windows: int, eb: int,
                        vb: int, ragged: bool = True):
    """One deterministic power-law stream per tenant; ragged lengths
    (a short partial tail on some tenants) exercise the right-padding
    path the slab exists for."""
    streams = {}
    for i in range(n_tenants):
        n = windows * eb
        if ragged and i % 3 == 2:
            n -= eb // 3  # partial final window
        s, d = make_stream(n, vb, seed=100 + i)
        streams["t%02d" % i] = (s.astype(np.int32), d.astype(np.int32))
    return streams


def sequential_oracle(streams, eb, vb, per_window: bool):
    """N single-tenant engines. per_window=True replays the serving
    shape (one process() call per arrived window, round-robin);
    False folds each stream in one chunked call."""
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    out = {}
    engines = {tid: StreamSummaryEngine(edge_bucket=eb,
                                        vertex_bucket=vb)
               for tid in streams}
    if not per_window:
        for tid, (s, d) in streams.items():
            out[tid] = engines[tid].process(s, d)
        return out
    out = {tid: [] for tid in streams}
    cursors = {tid: 0 for tid in streams}
    live = True
    while live:
        live = False
        for tid, (s, d) in streams.items():
            c = cursors[tid]
            if c >= len(s):
                continue
            hi = min(c + eb, len(s))
            # a trailing partial window is the stream's FINAL call —
            # exactly the count-based tumbling contract
            out[tid].extend(engines[tid].process(s[c:hi], d[c:hi]))
            cursors[tid] = hi
            live = True
    return out


def cohort_run(streams, eb, vb, per_window: bool):
    """The cohort side: admit everyone, feed in arrival order, pump.
    per_window=True feeds one window per tenant per round (the
    serving shape — every round is one vmapped dispatch); False
    preloads the queues and lets pump() catch up at its
    windows-per-dispatch ceiling."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort

    co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    for tid in streams:
        co.admit(tid)
    out = {tid: [] for tid in streams}
    cursors = {tid: 0 for tid in streams}
    live = True
    while live:
        live = False
        for tid, (s, d) in streams.items():
            c = cursors[tid]
            if c >= len(s):
                continue
            hi = min(c + eb, len(s)) if per_window \
                else min(c + 4 * eb, len(s))
            co.feed(tid, s[c:hi], d[c:hi])
            cursors[tid] = hi
            live = True
        for tid, res in co.pump().items():
            out[tid].extend(res)
    for tid in streams:
        out[tid].extend(co.close(tid))
    return out


def _probe(name: str, jax, streams, eb, vb, per_window: bool,
           results: list) -> None:
    total_edges = sum(len(s) for s, _d in streams.values())
    want = sequential_oracle(streams, eb, vb, per_window)
    got = cohort_run(streams, eb, vb, per_window)
    parity = all(digest_summaries(got[t]) == digest_summaries(want[t])
                 for t in streams)

    seq = timed_stats(
        lambda: sequential_oracle(streams, eb, vb, per_window),
        reps=3, warmup=0)
    coh = timed_stats(
        lambda: cohort_run(streams, eb, vb, per_window),
        reps=3, warmup=0)

    row = {
        "probe": name,
        "backend": jax.default_backend(),
        "tenants": len(streams),
        "eb": eb, "vb": vb,
        "num_edges": total_edges,
        "windows": sum(-(-len(s) // eb)
                       for s, _d in streams.values()),
        "tenant_edges_per_s": round(total_edges / coh[0]),
        "sequential_edges_per_s": round(total_edges / seq[0]),
        "parity": bool(parity),
        "tenant_digests": {t: digest_summaries(got[t])
                           for t in sorted(streams)},
    }
    _dispersion(row, "cohort", coh)
    _dispersion(row, "sequential", seq)
    if parity:
        row["speedup"] = round(seq[0] / coh[0], 3)
        row["speedup_worst"] = round(seq[1] / coh[2], 3)
        row["speedup_best"] = round(seq[2] / coh[1], 3)
    else:
        bad = [t for t in streams
               if digest_summaries(got[t]) != digest_summaries(want[t])]
        print("PARITY FAILURE (%s): tenants %s diverged from the "
              "sequential oracle" % (name, bad), file=sys.stderr)
    results.append(row)
    print(json.dumps(row), flush=True)


def smoke() -> int:
    """The ci_check gate: a 1-tenant cohort's digest must be
    byte-identical to a single StreamSummaryEngine's on the same
    stream (full + partial windows), in seconds not minutes."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    eb, vb = 512, 1024
    n = 5 * eb + eb // 4  # 5 full windows + a partial tail
    s, d = make_stream(n, vb, seed=11)
    s, d = s.astype(np.int32), d.astype(np.int32)
    want = StreamSummaryEngine(edge_bucket=eb,
                               vertex_bucket=vb).process(s, d)
    co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    co.admit("solo")
    got = []
    for lo in range(0, n, 2 * eb):
        co.feed("solo", s[lo:lo + 2 * eb], d[lo:lo + 2 * eb])
        got.extend(co.pump().get("solo", []))
    got.extend(co.close("solo"))
    if digest_summaries(got) != digest_summaries(want) \
            or len(got) != len(want):
        print("tenancy smoke FAILED: 1-tenant cohort digest %s != "
              "single-stream digest %s (%d vs %d windows)"
              % (digest_summaries(got), digest_summaries(want),
                 len(got), len(want)), file=sys.stderr)
        return 1
    print("tenancy smoke ok: 1-tenant cohort ≡ single stream (%s, "
          "%d windows)" % (digest_summaries(got), len(got)),
          flush=True)
    return 0


PROBE_NAMES = ("cohort_serving", "cohort_batch")


def commit_results(results, backend: str) -> None:
    """Merge this run's `tenancy_ab` rows into the committed evidence
    — the same policy as tools/resident_ab.py: PERF.json only when
    its backend label matches the live backend, the per-backend
    archive PERF_<backend>.json always."""
    targets = ((os.path.join(REPO, "PERF.json"), True),
               (os.path.join(REPO, "PERF_%s.json" % backend), False))
    for path, need_match in targets:
        try:
            with open(path) as f:
                cur = json.load(f)
        except (OSError, ValueError):
            cur = {}
        if need_match and cur.get("backend") != backend:
            print("not committing to %s: file backend %r != live %r"
                  % (os.path.basename(path), cur.get("backend"),
                     backend), file=sys.stderr)
            continue
        cur.setdefault("backend", backend)
        cur["tenancy_ab"] = results
        with open(path, "w") as f:
            json.dump(cur, f, indent=2)
        print("committed %s row(s) to %s"
              % (len(results), os.path.basename(path)), flush=True)


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("probes", nargs="*",
                    help="subset of %s to run (default: all)"
                         % (PROBE_NAMES,))
    ap.add_argument("--tenants", type=int,
                    default=int(os.environ.get("GS_AB_TENANTS", 8)))
    ap.add_argument("--windows", type=int,
                    default=int(os.environ.get("GS_AB_WINDOWS", 16)),
                    help="windows per tenant")
    ap.add_argument("--eb", type=int,
                    default=int(os.environ.get("GS_AB_EB", 512)))
    ap.add_argument("--vb", type=int,
                    default=int(os.environ.get("GS_AB_VB", 1024)))
    ap.add_argument("--smoke", action="store_true",
                    help="CI parity gate only: 1-tenant cohort must "
                         "equal the single-stream digest")
    ap.add_argument("--commit", action="store_true",
                    help="merge rows into PERF.json (backend-matched) "
                         "and PERF_<backend>.json")
    args = ap.parse_args()
    bad = [p for p in args.probes if p not in PROBE_NAMES]
    if bad:
        ap.error("unknown probe(s) %s; valid: %s"
                 % (bad, list(PROBE_NAMES)))
    want = args.probes or list(PROBE_NAMES)

    # measure the cross-tenant batching lever in isolation: the online
    # tuner changing dispatch knobs between reps would be noise here
    os.environ["GS_AUTOTUNE"] = "0"

    if args.smoke:
        sys.exit(smoke())

    import jax

    streams = make_tenant_streams(args.tenants, args.windows,
                                  args.eb, args.vb)
    results = []
    if "cohort_serving" in want:
        _probe("cohort_serving", jax, streams, args.eb, args.vb,
               True, results)
    if "cohort_batch" in want:
        _probe("cohort_batch", jax, streams, args.eb, args.vb,
               False, results)
    out = os.path.join(REPO, "logs",
                       "tenancy_ab_%s.json" % jax.default_backend())
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote %s" % out, flush=True)
    if args.commit:
        commit_results(results, jax.default_backend())


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Scale validation (VERDICT r1 item 7): a deterministic ≥10M-edge
timestamped stream pushed through the real ingest paths, with the
vertex domain growing past 2^16 mid-stream so the driver's bucket-
doubling (O(log V) recompiles, then steady state) is exercised at
scale. Emits one JSON line per leg and writes SCALE_r02.json.

Legs:
  driver   — StreamingAnalyticsDriver.stream_file (bounded-memory C++
             chunk parse -> event-time windows -> all four analytics),
             with a jax_log_compiles listener asserting NO compile
             lands in the steady-state tail of the stream.
  fused    — StreamSummaryEngine.process over the same edges (the
             one-dispatch-per-64-windows throughput path).
  sharded  — ShardedSummaryEngine on the virtual 8-device CPU mesh
             (subprocess; the backend pin must precede jax import).

The fixture file is generated to --out (default /tmp, ~190MB — the
GENERATOR is committed, the data is reproducible, BASELINE.json names
real datasets this zero-egress image cannot download).
"""

import argparse
import json
import logging
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np

NUM_EDGES = int(os.environ.get("GS_SCALE_EDGES", 10_000_000))
EDGES_PER_WINDOW = int(os.environ.get("GS_SCALE_WINDOW", 65_536))
WINDOW_MS = 1_000
V_START = 4_096       # driver's default vertex bucket: growth starts at once
# crosses 2^16 mid-stream -> bucket doubling under load
V_END = int(os.environ.get("GS_SCALE_VEND", 262_144))
SEED = 11


def generate(path: str) -> None:
    """Deterministic 'src dst ts' fixture: Zipf-ish endpoints over a
    vertex domain that widens linearly from V_START to V_END across the
    stream (new vertices keep arriving, the way a real edge stream's id
    space grows), timestamps ascending with exactly EDGES_PER_WINDOW
    edges per WINDOW_MS event-time window."""
    rng = np.random.default_rng(SEED)
    t0 = time.perf_counter()
    with open(path, "w") as f:
        at = 0
        while at < NUM_EDGES:
            n = min(EDGES_PER_WINDOW, NUM_EDGES - at)
            # domain grows with stream position; ranks drawn by inverse-
            # CDF of a power law (cheap, no per-draw choice(p=...))
            vmax = V_START + (V_END - V_START) * at // NUM_EDGES
            u = rng.random((2, n))
            ids = ((vmax ** u) - 1).astype(np.int64)  # ~Zipf over [0,vmax)
            ts = np.full(n, (at // EDGES_PER_WINDOW) * WINDOW_MS)
            # scatter hot ids over the space deterministically
            s = (ids[0] * 2654435761) % vmax
            d = (ids[1] * 2246822519) % vmax
            d = np.where(s == d, (d + 1) % vmax, d)
            np.savetxt(f, np.stack([s, d, ts], 1), fmt="%d")
            at += n
    print(json.dumps({
        "leg": "generate", "edges": NUM_EDGES, "path": path,
        "bytes": os.path.getsize(path),
        "seconds": round(time.perf_counter() - t0, 1)}), flush=True)


class CompileCounter(logging.Handler):
    """Counts XLA compiles via jax_log_compiles ('Finished tracing +
    compiling ...' records on the jax logger tree)."""

    def __init__(self):
        super().__init__()
        self.events = []

    def emit(self, record):
        msg = record.getMessage()
        if "compiling" in msg.lower():
            self.events.append(msg)


def run_driver(path: str) -> dict:
    import jax

    from gelly_streaming_tpu import StreamingAnalyticsDriver

    jax.config.update("jax_log_compiles", True)
    counter = CompileCounter()
    # handler ONLY on the ancestor: records issued on the child loggers
    # propagate up, so attaching to both would double-count
    logging.getLogger("jax").addHandler(counter)
    for name in ("jax._src.interpreters.pxla", "jax._src.dispatch"):
        logging.getLogger(name).setLevel(logging.DEBUG)

    drv = StreamingAnalyticsDriver(window_ms=WINDOW_MS, tracing=True)
    t0 = time.perf_counter()
    windows = 0
    total_w = NUM_EDGES // EDGES_PER_WINDOW
    last_result = None
    tail_at = max(1, (3 * total_w) // 4)
    # steady-state contract: programs come from a BOUNDED set. A tail
    # window may compile only if a bucket grew in it (the driver's
    # O(log V) growth recompiles are by design), with one exception:
    # the stream's final ragged flush legitimately first-uses a new
    # W-bucket / per-window program class, exactly once. A genuine
    # per-window leak compiles in MANY tail windows; so the assert is
    # on the number of DISTINCT no-growth windows that compiled.
    prev_events = 0
    prev_caps = (0, 0)
    violation_windows = []  # (window_idx, [compile msgs])
    tail_compiles = 0
    for res in drv.stream_file(path, chunk_bytes=1 << 26):
        windows += 1
        last_result = res
        caps = (drv.vb, drv.eb)
        new_events = len(counter.events) - prev_events
        if windows >= tail_at and new_events:
            tail_compiles += new_events
            if caps == prev_caps:
                violation_windows.append(
                    (windows,
                     counter.events[prev_events:prev_events
                                    + new_events]))
        prev_events = len(counter.events)
        prev_caps = caps
    elapsed = time.perf_counter() - t0
    jax.config.update("jax_log_compiles", False)

    assert len(violation_windows) <= 1, (
        "steady-state recompiles (no bucket growth) in %d tail "
        "windows — more than the final ragged flush can explain:\n%s"
        % (len(violation_windows),
           "\n".join(m for _w, ms in violation_windows for m in ms)))
    assert last_result is not None
    nv = len(last_result.vertex_ids)
    # the bucket must have grown to hold the fixture's final vertex
    # domain (past 2^16 at the real V_END=262144) — proves doubling
    # happened mid-stream, under load
    need = V_START
    while need < V_END // 2:
        need *= 2
    assert drv.vb >= need, (
        f"fixture never grew the vertex bucket (vb={drv.vb}, "
        f"expected >= {need} for a {V_END}-vertex domain)")
    return {
        "leg": "driver-stream_file",
        "backend": jax.default_backend(),
        "edges": NUM_EDGES,
        "windows": windows,
        "vertices_final": nv,
        "vertex_bucket_final": drv.vb,
        "edges_per_sec": round(NUM_EDGES / elapsed),
        "compiles_total": len(counter.events),
        "compiles_steady_state_tail": tail_compiles,
        "tail_windows_compiling_outside_bucket_growth":
            [w for w, _m in violation_windows],
        "trace": drv.trace_report(),
    }


def run_fused(path: str) -> dict:
    import jax

    from gelly_streaming_tpu.io.sources import load_edge_arrays
    from gelly_streaming_tpu.ops.scan_analytics import StreamSummaryEngine
    from gelly_streaming_tpu.ops.segment import intern

    src, dst, _ts = load_edge_arrays(path)
    _uniq, (s, d) = intern(src, dst)
    eng = StreamSummaryEngine(edge_bucket=EDGES_PER_WINDOW,
                              vertex_bucket=int(max(s.max(), d.max())) + 1)
    # compile both chunk shapes + the overflow fallback outside timing
    num_w = -(-len(s) // eng.eb)
    for w in {min(num_w, eng.MAX_WINDOWS), num_w % eng.MAX_WINDOWS}:
        if w:
            zeros = np.zeros(w * eng.eb, np.int32)
            eng.process(zeros, zeros)
            eng.reset()
    eng.warm_fallback()
    t0 = time.perf_counter()
    out = eng.process(s, d)
    elapsed = time.perf_counter() - t0
    return {
        "leg": "fused-scan",
        "backend": jax.default_backend(),
        "edges": len(s),
        "windows": len(out),
        "edges_per_sec": round(len(s) / elapsed),
        "final_summary": out[-1],
    }


def run_sharded(path: str, timeout_s: int = 3600) -> dict:
    code = r"""
import json, sys, time
import numpy as np
sys.path.insert(0, %(repo)r)
from gelly_streaming_tpu.core.platform import cpu_mesh
cpu_mesh(8)
from gelly_streaming_tpu.io.sources import load_edge_arrays
from gelly_streaming_tpu.ops.segment import intern
from gelly_streaming_tpu.parallel.mesh import make_mesh
from gelly_streaming_tpu.parallel.sharded import ShardedSummaryEngine

src, dst, _ts = load_edge_arrays(%(path)r)
# the virtual CPU mesh is a sharding-correctness leg, not a perf leg:
# an eighth of the stream bounds the wall-clock
n = len(src) // 8
_uniq, (s, d) = intern(src[:n], dst[:n])
eng = ShardedSummaryEngine(make_mesh(), edge_bucket=%(epw)d,
                           vertex_bucket=int(max(s.max(), d.max())) + 1)
zeros = np.zeros(min(-(-len(s) // eng.eb), eng.MAX_WINDOWS) * eng.eb,
                 np.int32)
eng.process(zeros, zeros)
eng.reset()
eng.warm_fallback()
t0 = time.perf_counter()
out = eng.process(s, d)
elapsed = time.perf_counter() - t0
print(json.dumps({
    "leg": "sharded-fused-scan", "backend": "cpu-virtual-mesh",
    "devices": 8, "edges": len(s), "windows": len(out),
    "edges_per_sec": round(len(s) / elapsed),
    "final_summary": out[-1]}))
""" % {"repo": REPO, "path": path, "epw": EDGES_PER_WINDOW}
    # PYTHONPATH stripped: the baked sitecustomize dials the (possibly
    # wedged) PJRT relay from every child; the code above sys.path-
    # inserts the repo itself. run_json_child kills the process GROUP
    # on timeout so a hung child costs one leg, not the run.
    from bench import run_json_child

    from bench import clean_cpu_env

    env = clean_cpu_env(
        XLA_FLAGS="--xla_force_host_platform_device_count=8")
    got = run_json_child([sys.executable, "-c", code], timeout_s, env=env)
    if "error" in got:
        got["leg"] = "sharded-fused-scan"
    return got


def run_citation(_path: str) -> dict:
    """Real-shaped leg (VERDICT r2 missing-3): the cit-HepPh-calibrated
    citation stream (utils/realgraph.py — exact published node/edge
    counts, clustering/triangles within a few percent of the SNAP
    figures, power-law degree tail, DAG timestamps) through the
    driver's batched path. The synthetic legs above characterize
    scale; this one pins throughput on real-graph shape, where hub
    rows and co-citation clustering stress the K-bucket ladder."""
    import jax

    from gelly_streaming_tpu import StreamingAnalyticsDriver
    from gelly_streaming_tpu.utils.realgraph import citation_stream

    src, dst, _ts = citation_stream()
    vb = int(max(src.max(), dst.max())) + 1
    eb = 8_192
    drv = StreamingAnalyticsDriver(window_ms=0, edge_bucket=eb,
                                   vertex_bucket=vb)
    # warm with the REAL stream, not zeros: the citation graph's hub
    # windows overflow the tuned starting K, so the escalation-rung
    # programs (and the exact-recount path) are part of what the timed
    # run executes — a zero-stream warm-up would leave them to compile
    # inside the timing
    drv.run_arrays(src, dst)
    drv.reset()
    t0 = time.perf_counter()
    res = drv.run_arrays(src, dst)
    elapsed = time.perf_counter() - t0
    return {
        "leg": "citation-driver",
        "backend": jax.default_backend(),
        "graph": "cit-HepPh-calibrated (gelly_streaming_tpu/utils/"
                 "realgraph.py; SNAP-published anchors)",
        "edges": len(src),
        "vertices": vb,
        "windows": len(res),
        "edges_per_sec": round(len(src) / elapsed),
        "window_triangles_last": res[-1].triangles,
    }


LEGS = {"driver": run_driver, "fused": run_fused, "sharded": run_sharded,
        "citation": run_citation}


def run_leg_subprocess(leg: str, fixture: str, timeout_s: int,
                       env=None) -> dict:
    """Run one leg in its own process group with a hard timeout (same
    contract as tools/profile_kernels.py sections: a wedged remote
    compile costs one leg, not the whole scale run). `sharded` already
    subprocesses itself with a CPU pin, so it runs in-process here."""
    from bench import run_json_child

    if leg == "sharded":
        return run_sharded(fixture, timeout_s)
    got = run_json_child(
        [sys.executable, os.path.abspath(__file__), "--leg", leg,
         "--out", fixture], timeout_s, env=env, require_key="leg")
    if "error" in got:
        got["leg"] = leg
    return got


def _chip(legs) -> bool:
    return any(leg.get("backend") == "tpu" for leg in legs)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/tmp/gs_scale_fixture.txt")
    ap.add_argument("--leg", help="child mode: run ONE leg in-process")
    ap.add_argument("legs", nargs="*",
                    default=["driver", "fused", "sharded", "citation"])
    args = ap.parse_args()

    if not os.path.exists(args.out):
        generate(args.out)
    if args.leg:
        print(json.dumps(LEGS[args.leg](args.out)), flush=True)
        return

    unknown = [leg for leg in args.legs if leg not in LEGS]
    if unknown:
        sys.exit("unknown leg(s) %s; valid: %s" % (unknown, list(LEGS)))
    timeout_s = int(os.environ.get("GS_SCALE_LEG_TIMEOUT", "3600"))
    out_path = os.path.join(REPO, "SCALE_r02.json")
    try:
        with open(out_path) as f:
            prior = json.load(f)
    except (OSError, ValueError):
        prior = None

    results = {"num_edges": NUM_EDGES, "edges_per_window": EDGES_PER_WINDOW,
               "v_start": V_START, "v_end": V_END, "seed": SEED,
               "legs": []}
    wrote = [None]

    def flush():
        # Same no-clobber contract as profile_kernels' PERF.json: the
        # committed scale evidence must never be degraded.
        #  - prior at a LARGER scale -> this (dev/test) run stays in
        #    .partial; legs from different NUM_EDGES are not
        #    comparable under one meta block;
        #  - prior with IDENTICAL meta (every generator parameter, not
        #    just num_edges — they are all env-overridable) -> merge
        #    per-leg, where a cpu-fallback leg never replaces a chip-
        #    measured one and a failed leg keeps the prior version;
        #  - otherwise (smaller/absent/incomparable-meta prior) ->
        #    fresh whole-file replace once any leg succeeded, unless
        #    that would swap chip evidence for a cpu fallback.
        meta_keys = ("num_edges", "edges_per_window", "v_start",
                     "v_end", "seed")
        new_ok = [leg for leg in results["legs"] if "error" not in leg]
        merged = dict(results)
        usable = bool(new_ok) and not (
            prior is not None and _chip(prior.get("legs", []))
            and not _chip(new_ok))
        if prior is not None and prior.get("num_edges", 0) > NUM_EDGES:
            usable = False
        elif prior is not None and all(
                prior.get(k) == results[k] for k in meta_keys):
            by_name = {leg.get("leg"): leg
                       for leg in prior.get("legs", [])}
            replaced = 0
            for leg in new_ok:
                old = by_name.get(leg["leg"])
                if (old is not None and old.get("backend") == "tpu"
                        and leg.get("backend") != "tpu"):
                    continue   # cpu fallback never replaces a chip leg
                by_name[leg["leg"]] = leg
                replaced += 1
            for leg in results["legs"]:
                if "error" in leg and leg["leg"] not in by_name:
                    by_name[leg["leg"]] = leg
            merged["legs"] = list(by_name.values())
            usable = replaced > 0
        path = out_path if usable else out_path + ".partial"
        with open(path, "w") as f:
            json.dump(merged if usable else results, f, indent=2)
        wrote[0] = path

    # Probe once: with a wedged tunnel even JAX_PLATFORMS=cpu hangs in
    # this image (the baked sitecustomize dials the PJRT relay from
    # every process), so the CPU fallback must ALSO strip PYTHONPATH to
    # drop the plugin registration entirely. Legs report the backend
    # they actually ran on, so a fallback is labeled cpu, never chip.
    from bench import probe_backend

    child_env = None
    if any(leg != "sharded" for leg in args.legs):
        if probe_backend() is None:
            print("no chip backend; legs fall back to clean-CPU env",
                  file=sys.stderr)
            from bench import clean_cpu_env

            child_env = clean_cpu_env()
    for leg in args.legs:
        r = run_leg_subprocess(leg, args.out, timeout_s, env=child_env)
        results["legs"].append(r)
        print(json.dumps(r), flush=True)
        flush()
    print("wrote %s" % wrote[0], file=sys.stderr)


if __name__ == "__main__":
    main()

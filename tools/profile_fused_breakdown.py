#!/usr/bin/env python
"""Fused-scan vs driver: per-stage breakdown on identical input
(VERDICT r3 weak-4: the fused engine — built to beat the driver on
dispatch count — measured ~17% SLOWER on CPU; find where the seconds
go instead of hand-waving).

Decomposition on one quiet-host CPU run, same stream for every leg:

  driver          — StreamingAnalyticsDriver batched path, tracing on:
                    per-stage exclusive seconds (intern, snapshot_scan,
                    triangles, ...) from its StepTimer.
  fused           — StreamSummaryEngine.process as shipped (triangles
                    INSIDE the XLA scan program).
  fused_no_tri    — the same scan with the triangle stage compiled out
                    (degrees+CC+bipartite only): isolates what the
                    in-scan triangle intersect costs.
  tri_host_tier   — the driver's triangle route on a CPU backend: the
                    measurement-selected numpy tier
                    (ops/host_triangles.py), on the same windows.
  tri_xla_stream  — TriangleWindowKernel._count_stream_device: the
                    SAME XLA triangle program the fused scan embeds,
                    standalone.

The hypothesis this measures: on a 1-core CPU host the driver's
triangles ride the numpy host tier (~4.5x faster than XLA's intersect
on this host, PERF.json host_stream) while the fused engine is
structurally stuck with XLA triangles inside its scan; CPU dispatch
costs ~µs, so fusing dispatches buys nothing back. On chip (0.2s
tunnel dispatch latency, MXU intersect) the economics invert — which
is why the fused engine stays the chip-side throughput path.

Writes FUSED_BREAKDOWN.json and prints one JSON line per leg.
Run on a QUIET host (single core: any background load lands directly
in these numbers).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from gelly_streaming_tpu.core.platform import use_cpu  # noqa: E402

use_cpu()

import numpy as np  # noqa: E402


def _stream(num_edges, num_vertices, seed=7):
    rng = np.random.default_rng(seed)
    src = rng.zipf(1.9, num_edges).astype(np.int64) % num_vertices
    dst = (src + 1 + rng.zipf(1.9, num_edges).astype(np.int64)
           % (num_vertices - 1)) % num_vertices
    return src, dst


def _timeit(fn, reps=3, warmup=1):
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def main():
    import jax

    from gelly_streaming_tpu import StreamingAnalyticsDriver
    from gelly_streaming_tpu.ops import scan_analytics, segment as seg_ops
    from gelly_streaming_tpu.ops import triangles as tri_ops

    eb = int(os.environ.get("GS_FB_EB", 8192))
    num_w = int(os.environ.get("GS_FB_WINDOWS", 64))
    vb = 2 * eb
    src, dst = _stream(num_w * eb, vb)
    rows = []

    def emit(row):
        rows.append(row)
        print(json.dumps(row), flush=True)

    emit({"leg": "config", "backend": jax.default_backend(),
          "edge_bucket": eb, "windows": num_w, "vertex_bucket": vb,
          "edges": num_w * eb})

    # ---- driver, tracing on: per-stage exclusive seconds
    drv = StreamingAnalyticsDriver(window_ms=0, edge_bucket=eb,
                                   vertex_bucket=vb, tracing=True)
    drv.run_arrays(src, dst)  # warm (compiles + host-tier selection)

    from gelly_streaming_tpu.utils.tracing import StepTimer

    def run_driver():
        drv.reset()
        drv.timer = StepTimer()   # per-rep stage totals (last rep kept)
        drv.run_arrays(src, dst)

    t = _timeit(run_driver)
    emit({"leg": "driver", "seconds": round(t, 3),
          "edges_per_s": round(num_w * eb / t),
          "stages": {r["op"]: {
              "seconds": round(r["total_s"], 3),
              "pct": round(100 * r["total_s"] / t, 1)}
              for r in drv.trace_report()}})

    # ---- fused engine as shipped
    eng = scan_analytics.StreamSummaryEngine(edge_bucket=eb,
                                             vertex_bucket=vb)
    eng.warm_fallback()

    def run_fused():
        eng.reset()
        eng.process(src, dst)

    t_fused = _timeit(run_fused)
    emit({"leg": "fused", "seconds": round(t_fused, 3),
          "edges_per_s": round(num_w * eb / t_fused),
          "k_bucket": eng.kb})

    # ---- the same scan WITHOUT the triangle stage: what does the
    # in-scan XLA intersect cost? (built inline: same body minus tri)
    import jax.numpy as jnp

    from gelly_streaming_tpu.ops import unionfind

    sent = vb

    def body_no_tri(carry, xs):
        deg, labels, cover = carry
        s_, d_, valid = xs
        s = jnp.where(valid, s_, sent)
        d = jnp.where(valid, d_, sent)
        ones = jnp.where(valid, 1, 0)
        deg = deg + (jax.ops.segment_sum(ones, s, vb + 1)
                     + jax.ops.segment_sum(ones, d, vb + 1))
        max_degree = jnp.max(deg[:vb])
        labels = unionfind.cc_fixpoint(labels, s, d)
        touched = deg[:vb] > 0
        num_components = jnp.sum(
            touched & (labels[:vb] == jnp.arange(vb)), dtype=jnp.int32)
        cover = unionfind.cc_fixpoint(
            cover, jnp.concatenate([s, s + (vb + 1)]),
            jnp.concatenate([d + (vb + 1), d]))
        odd = jnp.any(touched & (cover[:vb] == cover[vb + 1:2 * vb + 1]))
        return (deg, labels, cover), (max_degree, num_components, odd)

    @jax.jit
    def run_scan_no_tri(carry, s_w, d_w, valid_w):
        return jax.lax.scan(body_no_tri, carry, (s_w, d_w, valid_w))

    _, s_w, d_w, valid_w = seg_ops.window_stack(src, dst, eb,
                                                sentinel=vb)
    carry0 = (jnp.zeros(vb + 1, jnp.int32),
              jnp.arange(vb + 1, dtype=jnp.int32),
              jnp.arange(2 * (vb + 1), dtype=jnp.int32))
    s_j, d_j, v_j = (jnp.asarray(x) for x in (s_w, d_w, valid_w))

    def run_no_tri():
        c, outs = run_scan_no_tri(carry0, s_j, d_j, v_j)
        jax.block_until_ready(outs)

    t_no_tri = _timeit(run_no_tri)
    emit({"leg": "fused_no_tri", "seconds": round(t_no_tri, 3),
          "edges_per_s": round(num_w * eb / t_no_tri),
          "implied_in_scan_triangle_seconds": round(t_fused - t_no_tri,
                                                    3)})

    # ---- the driver's CPU triangle route: numpy host tier
    from gelly_streaming_tpu.ops import host_triangles

    def run_host_tri():
        host_triangles.count_stream(src, dst, eb)

    t_host = _timeit(run_host_tri)
    emit({"leg": "tri_host_tier", "seconds": round(t_host, 3),
          "edges_per_s": round(num_w * eb / t_host)})

    # ---- the standalone XLA triangle stream program (what the fused
    # scan embeds), selection bypassed
    kern = tri_ops.TriangleWindowKernel(edge_bucket=eb,
                                        vertex_bucket=vb)
    kern._count_stream_device(src, dst)  # warm

    def run_xla_tri():
        kern._count_stream_device(src, dst)

    t_xla = _timeit(run_xla_tri)
    emit({"leg": "tri_xla_stream", "seconds": round(t_xla, 3),
          "edges_per_s": round(num_w * eb / t_xla),
          "k_bucket": kern.kb})

    # ---- the verdict, computed not asserted
    emit({"leg": "analysis",
          "fused_minus_no_tri_s": round(t_fused - t_no_tri, 3),
          "xla_vs_host_tri_ratio": round(t_xla / t_host, 2),
          "driver_wins_because":
              "driver = scan(no tri) + host-tier triangles + host "
              "assembly; fused = scan WITH XLA triangles. On this "
              "backend XLA intersect costs %.1fx the numpy tier and "
              "dispatch latency is negligible, so fusing cannot pay "
              "for it." % (t_xla / max(t_host, 1e-9))})
    with open(os.path.join(REPO, "FUSED_BREAKDOWN.json"), "w") as f:
        json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()

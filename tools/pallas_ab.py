#!/usr/bin/env python
"""Fused-window-megakernel A/B: does the Pallas window megakernel
(ops/pallas_window.py) beat the XLA scan-of-gathers end-to-end, with
EXACT parity?

Two committed probes, each a JSON row in the `pallas_ab` section:

  engine_pallas — StreamSummaryEngine over the canonical 524K/32768
              row: GS_PALLAS_WINDOW=on (the megakernel body) vs off
              (the XLA fused scan), window-by-window sha256 parity
              of the summary dicts, plus the numpy host twin
              (parallel/host_twin.HostSummaryEngine) as the
              tier-independent oracle.
  stream_pallas — TriangleWindowKernel._count_stream_device (the
              tier selection bypassed, so the device program is
              measured on every backend): megakernel counter vs XLA
              counter, exact count parity against
              ops/host_triangles.count_stream.

Timing is median-of-3 with min/max dispersion committed in the row
(the ingress A/B's 1.13x/1.02x flip-flop taught us a single run is
load noise, not evidence). GS_AUTOTUNE is pinned OFF inside the
probes so the kernel lever is measured in isolation.

The committed rows are what ops/pallas_window.resolve_pallas_window
gates on: parity true AND `speedup` ≥1.05 on EVERY row, or the XLA
scan stands. On a CPU backend the kernel runs in INTERPRET mode —
parity is real evidence there, speed is not (interpret rows
committed from a CPU run can never honestly clear the bar, and the
backend-matched loader keeps them from ever driving a chip
selection). Commit policy identical to tools/resident_ab.py.

--sweep drives the `pallas_window` DispatchTuner family (edge-tile ×
K-chunk arms) through two full measurement passes and persists the
winning arm to the per-backend tuning cache, which
pallas_window.resolve_tiles seeds production builds from — run it in
the chip window before `--commit`.
"""

import hashlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from bench import make_stream  # noqa: E402
from tools.egress_ab import _dispersion, timed_stats  # noqa: E402


def _pin(value: str):
    """Flip the selection pin and drop the memoized verdicts/programs
    so each leg builds exactly what it claims to measure."""
    from gelly_streaming_tpu.ops import pallas_window as pw

    os.environ["GS_PALLAS_WINDOW"] = value
    pw._reset_pallas_window()


def _digest_summaries(summaries) -> str:
    h = hashlib.sha256()
    for s in summaries:
        h.update(json.dumps(s, sort_keys=True).encode())
    return h.hexdigest()[:16]


def engine_pallas(jax, num_edges, results):
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)
    from gelly_streaming_tpu.parallel.host_twin import (
        HostSummaryEngine)

    eb, vb = 32768, 65536
    src, dst = make_stream(num_edges, vb)
    s32, d32 = src.astype(np.int32), dst.astype(np.int32)

    def build(pin):
        _pin(pin)
        return StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)

    engines = {"pallas": build("on"), "xla": build("off")}
    digests, pallas_used = {}, None
    for name, eng in engines.items():
        digests[name] = _digest_summaries(eng.process(s32, d32))
        if name == "pallas":
            pallas_used = bool(eng._pallas)
        eng.reset()
    host = HostSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    digests["host"] = _digest_summaries(host.process(s32, d32))
    parity = (pallas_used
              and len(set(digests.values())) == 1)

    stats = {}
    for name, eng in engines.items():
        _pin("on" if name == "pallas" else "off")

        def run(eng=eng):
            eng.reset()
            eng.process(s32, d32)

        stats[name] = timed_stats(run, reps=3, warmup=0)
    _pin("")

    row = {
        "probe": "engine_pallas",
        "backend": jax.default_backend(),
        "interpret": jax.default_backend() != "tpu",
        "num_edges": len(src), "eb": eb, "vb": vb,
        "kb": engines["pallas"].kb,
        "ingress": engines["pallas"].ingress,
        "pallas_edges_per_s": round(len(src) / stats["pallas"][0]),
        "xla_edges_per_s": round(len(src) / stats["xla"][0]),
        "parity": bool(parity),
    }
    for name in stats:
        _dispersion(row, name, stats[name])
    if parity:
        row["speedup"] = round(stats["xla"][0] / stats["pallas"][0], 3)
        row["speedup_worst"] = round(
            stats["xla"][1] / stats["pallas"][2], 3)
        row["speedup_best"] = round(
            stats["xla"][2] / stats["pallas"][1], 3)
    else:
        print("PARITY FAILURE between window bodies (engine)"
              if pallas_used else
              "megakernel body was NOT selected (gate/probe refused)",
              file=sys.stderr)
    results.append(row)
    print(json.dumps(row), flush=True)


def stream_pallas(jax, num_edges, results):
    from gelly_streaming_tpu.ops import host_triangles
    from gelly_streaming_tpu.ops import triangles as tri_ops

    eb, vb = 32768, 65536
    src, dst = make_stream(num_edges, vb, seed=5)
    s32, d32 = src.astype(np.int32), dst.astype(np.int32)

    def build(pin):
        _pin(pin)
        return tri_ops.TriangleWindowKernel(edge_bucket=eb,
                                            vertex_bucket=vb)

    kernels = {"pallas": build("on"), "xla": build("off")}
    counts = {name: k._count_stream_device(s32, d32)
              for name, k in kernels.items()}
    counts["host"] = host_triangles.count_stream(s32, d32, eb)
    pallas_used = bool(kernels["pallas"]._pallas_counter)
    parity = (pallas_used
              and counts["pallas"] == counts["xla"] == counts["host"])

    stats = {}
    for name, k in kernels.items():
        _pin("on" if name == "pallas" else "off")
        stats[name] = timed_stats(
            lambda k=k: k._count_stream_device(s32, d32),
            reps=3, warmup=0)
    _pin("")

    row = {
        "probe": "stream_pallas",
        "backend": jax.default_backend(),
        "interpret": jax.default_backend() != "tpu",
        "num_edges": len(src), "eb": eb, "vb": vb,
        "kb": kernels["pallas"].kb,
        "pallas_edges_per_s": round(len(src) / stats["pallas"][0]),
        "xla_edges_per_s": round(len(src) / stats["xla"][0]),
        "parity": bool(parity),
    }
    for name in stats:
        _dispersion(row, name, stats[name])
    if parity:
        row["speedup"] = round(stats["xla"][0] / stats["pallas"][0], 3)
        row["speedup_worst"] = round(
            stats["xla"][1] / stats["pallas"][2], 3)
        row["speedup_best"] = round(
            stats["xla"][2] / stats["pallas"][1], 3)
    else:
        print("PARITY FAILURE between stream counters"
              if pallas_used else
              "megakernel counter was NOT selected (gate/probe "
              "refused)", file=sys.stderr)
    results.append(row)
    print(json.dumps(row), flush=True)


def sweep_tiles(jax, num_edges) -> None:
    """Drive the `pallas_window` DispatchTuner family (edge-tile ×
    K-chunk arms) through two full measurement passes over the arm
    grid and persist the incumbent to the per-backend tuning cache
    (GS_TUNE_CACHE) — the committed-evidence seed
    pallas_window.resolve_tiles builds production kernels from. NOT a
    committed PERF row: the cache is the artifact."""
    import itertools

    from gelly_streaming_tpu.ops import pallas_window as pw
    from gelly_streaming_tpu.ops import scan_analytics as sa
    from gelly_streaming_tpu.ops import triangles as tri_ops

    eb, vb = 32768, 65536
    edges = min(num_edges, 8 * eb)  # two passes × |arms| engine runs
    src, dst = make_stream(edges, vb, seed=9)
    s32, d32 = src.astype(np.int32), dst.astype(np.int32)
    kb = tri_ops._tuned_kb(eb)
    tuner = pw.tile_tuner(eb, vb, kb)
    arms = [dict(zip(tuner.space, vals)) for vals in
            itertools.product(*(tuner.space[k]
                                for k in tuner.space))]
    try:
        for _pass in range(2):
            for arm in arms:
                # the tile pins are how an arm reaches the engine's
                # build (pallas_window.resolve_tiles reads them at
                # body-build time, explicit pins beating the cache)
                os.environ["GS_PALLAS_TILE"] = str(arm["tile_e"])
                os.environ["GS_PALLAS_CK"] = str(arm["ck"])
                _pin("on")
                eng = sa.StreamSummaryEngine(edge_bucket=eb,
                                             vertex_bucket=vb)
                if not eng._pallas:
                    print("arm %s: megakernel refused (probe) — "
                          "skipping" % json.dumps(arm),
                          file=sys.stderr)
                    continue

                def run():
                    eng.reset()
                    eng.process(s32, d32)

                med, _lo, _hi = timed_stats(run, reps=1, warmup=1)
                tuner.record(arm, len(s32), med)
                print(json.dumps({"arm": arm,
                                  "edges_per_s": round(len(s32)
                                                       / med)}),
                      flush=True)
    finally:
        os.environ.pop("GS_PALLAS_TILE", None)
        os.environ.pop("GS_PALLAS_CK", None)
        _pin("")
    tuner.save()
    print("sweep incumbent: %s" % json.dumps(tuner.best()),
          flush=True)


PROBE_NAMES = ("engine_pallas", "stream_pallas")


def commit_results(results, backend: str) -> None:
    """Merge this run's `pallas_ab` rows into the committed evidence
    — the same policy as tools/resident_ab.py: PERF.json only when
    its backend label matches the live backend, the per-backend
    archive PERF_<backend>.json always."""
    targets = ((os.path.join(REPO, "PERF.json"), True),
               (os.path.join(REPO, "PERF_%s.json" % backend), False))
    for path, need_match in targets:
        try:
            with open(path) as f:
                cur = json.load(f)
        except (OSError, ValueError):
            cur = {}
        if need_match and cur.get("backend") != backend:
            print("not committing to %s: file backend %r != live %r"
                  % (os.path.basename(path), cur.get("backend"),
                     backend), file=sys.stderr)
            continue
        cur.setdefault("backend", backend)
        cur["pallas_ab"] = results
        with open(path, "w") as f:
            json.dump(cur, f, indent=2)
        print("committed %s row(s) to %s"
              % (len(results), os.path.basename(path)), flush=True)


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("probes", nargs="*",
                    help="subset of %s to run (default: all)"
                         % (PROBE_NAMES,))
    ap.add_argument("--edges", type=int,
                    default=int(os.environ.get("GS_AB_EDGES",
                                               524_288)))
    ap.add_argument("--sweep", action="store_true",
                    help="drive the pallas_window tile tuner over "
                         "its arm grid and persist the optimum "
                         "(chip-window prelude to --commit)")
    ap.add_argument("--commit", action="store_true",
                    help="merge rows into PERF.json "
                         "(backend-matched) and PERF_<backend>.json")
    args = ap.parse_args()
    bad = [p for p in args.probes if p not in PROBE_NAMES]
    if bad:
        ap.error("unknown probe(s) %s; valid: %s"
                 % (bad, list(PROBE_NAMES)))
    want = args.probes or list(PROBE_NAMES)

    # measure the kernel lever in isolation: the online tuner
    # changing dispatch knobs between reps would be noise here
    os.environ["GS_AUTOTUNE"] = "0"

    import jax

    if args.sweep:
        sweep_tiles(jax, args.edges)
    results = []
    if "engine_pallas" in want:
        engine_pallas(jax, args.edges, results)
    if "stream_pallas" in want:
        stream_pallas(jax, args.edges, results)
    out = os.path.join(REPO, "logs",
                       "pallas_ab_%s.json" % jax.default_backend())
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote %s" % out, flush=True)
    if args.commit:
        commit_results(results, jax.default_backend())


if __name__ == "__main__":
    main()

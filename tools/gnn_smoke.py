#!/usr/bin/env python
"""CI gate [12/12]: windowed-GNN round smoke.

One window through GnnSummaryEngine must leave a feature slab AND a
summary stream bit-identical to the numpy host twin (the lattice
bit-exactness oracle of ops/gnn_window) — so the static gate catches
a broken lattice edit (a rescaled weight snap, a reordered clip, an
aggregation that left the exact-shift regime) without a chip. A
second leg pins the fused Pallas GNN kernel (GS_GNN_PALLAS=on,
interpret mode off-TPU) to the same digests, and — like gate 7 —
exits non-zero if the kernel was NOT actually selected: a silently
refused probe must fail the gate rather than quietly re-test XLA
against itself.

Usage: JAX_PLATFORMS=cpu python tools/gnn_smoke.py
"""

import hashlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def _digest(summaries, slab) -> str:
    h = hashlib.sha256()
    for s in summaries:
        h.update(json.dumps(s, sort_keys=True).encode())
    h.update(np.ascontiguousarray(slab, np.float32).tobytes())
    return h.hexdigest()[:16]


def _run(cls, eb, vb, F, src, dst):
    from gelly_streaming_tpu.ops import gnn_window as gw

    eng = cls(eb, vb, feature_dim=F)
    rng = np.random.RandomState(3)
    eng.set_weights(rng.randn(F, F) * 0.3, rng.randn(F) * 0.1)
    eng.load_feature_units(gw.default_features(vb, F, seed=5))
    out = eng.process(src, dst)
    return _digest(out, eng.state()), eng


def main() -> int:
    os.environ.setdefault("GS_AUTOTUNE", "0")
    from gelly_streaming_tpu.ops import gnn_window as gw
    from gelly_streaming_tpu.ops import pallas_window as pw

    eb = vb = 256
    F = 16
    rng = np.random.default_rng(42)
    src = rng.integers(0, vb - 8, eb).astype(np.int32)
    dst = rng.integers(0, vb - 8, eb).astype(np.int32)

    want, _ = _run(gw.GnnHostEngine, eb, vb, F, src, dst)

    os.environ["GS_GNN_PALLAS"] = "off"
    pw._reset_pallas_window()
    got, _ = _run(gw.GnnSummaryEngine, eb, vb, F, src, dst)
    if got != want:
        print("gnn_smoke: DIGEST MISMATCH device %s != host twin %s "
              "(the lattice exactness contract is broken)"
              % (got, want))
        return 1

    os.environ["GS_GNN_PALLAS"] = "on"
    pw._reset_pallas_window()
    peng = gw.GnnSummaryEngine(eb, vb, feature_dim=F)
    if not peng._pallas:
        print("gnn_smoke: fused GNN kernel NOT selected under "
              "GS_GNN_PALLAS=on (build/trace probe refused — see "
              "the durable selection.fallback event)")
        return 1
    pgot, _ = _run(gw.GnnSummaryEngine, eb, vb, F, src, dst)
    os.environ.pop("GS_GNN_PALLAS", None)
    pw._reset_pallas_window()
    if pgot != want:
        print("gnn_smoke: DIGEST MISMATCH pallas %s != host twin %s"
              % (pgot, want))
        return 1

    print("gnn_smoke: ok (1 window, digest %s, xla ≡ pallas ≡ numpy "
          "twin slab+summaries)" % want)
    return 0


if __name__ == "__main__":
    sys.exit(main())

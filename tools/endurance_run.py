#!/usr/bin/env python
"""100M-edge endurance leg (VERDICT r3 item 8): prove stream_file's
bounded-memory / O(log) recompile claims (core/driver.py:252-265) at
10x the scale_run fixture, with a mid-stream crash + checkpoint resume.

One pass over a 100M-edge generated file (same recipe as
tools/scale_run.generate, 10x longer), all four analytics:

  phase A — driver with auto-checkpoint every CKPT_EVERY windows
            consumes the stream until CRASH_AT windows, then is
            abandoned mid-iteration (a simulated hard crash: no
            flush, no state handoff).
  phase B — a FRESH driver try_resume()s the newest checkpoint and
            re-feeds the same file with resume=True; the skip cursor
            must land it exactly where the checkpoint left off.

Measured throughout: RSS at every window batch (from /proc/self/status
— the bounded-memory ceiling), XLA compile events (jax_log_compiles —
steady-state tail must be compile-free), the metrics plane's memory
gauges (utils/metrics.sample_memory: live device buffers + bytes,
sampled per round — the soak FAILS on monotonic live-buffer growth,
the leak detector the resident-state megakernel work will lean on),
and end-of-stream invariants (windows_done * window size ==
edges_done == NUM_EDGES; sum(degrees) == 2 * edges folded since the
degree vector's birth).

Emits one JSON line per phase and writes ENDURANCE_r05.json
(override with --out).
CPU-fallback friendly: backend is whatever jax picks (the claim under
test is the host-side streaming discipline, not chip speed).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NUM_EDGES = int(os.environ.get("GS_END_EDGES", 100_000_000))
EDGES_PER_WINDOW = 65_536
CKPT_EVERY = 64            # windows between checkpoints
SEED_TAG = "endurance"

os.environ["GS_SCALE_EDGES"] = str(NUM_EDGES)
os.environ.setdefault("GS_SCALE_WINDOW", str(EDGES_PER_WINDOW))
os.environ.setdefault("GS_SCALE_VEND", "262144")

from tools.scale_run import CompileCounter, generate  # noqa: E402


def rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024.0
    return float("nan")


def check_buffer_leak(samples) -> dict:
    """The leak detector over phase B's per-round live-buffer counts:
    quarter means that grow MONOTONICALLY (and meaningfully — jit
    caches and carried state legitimately plateau) fail the soak."""
    import numpy as np

    counts = [s["live_buffers"] for s in samples
              if s.get("live_buffers") is not None]
    row = {"leg": "endurance_memory_gauges", "rounds": len(counts)}
    if len(counts) < 8:
        row.update({"ok": True, "note": "too few samples to judge"})
        return row
    quarters = [float(np.mean(q))
                for q in np.array_split(np.array(counts), 4)]
    monotonic = all(b > a for a, b in zip(quarters, quarters[1:]))
    growth = quarters[-1] - quarters[0]
    leak = monotonic and growth > max(8.0, 0.05 * quarters[0])
    row.update({
        "quarter_mean_live_buffers": [round(q, 1) for q in quarters],
        "live_buffer_bytes_last": samples[-1].get("live_buffer_bytes"),
        "ok": not leak,
    })
    assert not leak, ("monotonic live-buffer growth across the soak "
                      "— a device-buffer leak: %r" % row)
    return row


def run(fixture: str, out_path: str) -> None:
    import logging

    import jax
    import numpy as np

    from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
    from gelly_streaming_tpu.utils import metrics

    jax.config.update("jax_log_compiles", True)
    counter = CompileCounter()
    logging.getLogger("jax").addHandler(counter)

    total_windows = (NUM_EDGES + EDGES_PER_WINDOW - 1) // EDGES_PER_WINDOW
    crash_at = total_windows // 2
    # derive from the fixture path: a differently-sized rerun in the
    # same directory must never resume another run's stale checkpoint
    ckpt = fixture + ".%d.ckpt" % NUM_EDGES
    rows = []

    def leg(name):
        t0 = time.perf_counter()
        rss_samples = []
        compiles_before = len(counter.events)

        def finish(driver, windows, edges, tail_compiles):
            row = {
                "leg": name,
                "backend": jax.default_backend(),
                "windows": windows,
                "edges": edges,
                "seconds": round(time.perf_counter() - t0, 1),
                "edges_per_s": round(edges / max(
                    time.perf_counter() - t0, 1e-9)),
                "rss_mb_p10": round(float(np.percentile(rss_samples, 10))),
                "rss_mb_max": round(max(rss_samples)),
                "compiles": len(counter.events) - compiles_before,
                "compiles_steady_state_tail": tail_compiles,
                "windows_done": driver.windows_done,
                "edges_done": driver.edges_done,
            }
            rows.append(row)
            print(json.dumps(row), flush=True)
            return row

        return rss_samples, finish

    # ---- phase A: run to the crash point under auto-checkpoint
    drv = StreamingAnalyticsDriver(window_ms=1000)
    drv.enable_auto_checkpoint(ckpt, every_n_windows=CKPT_EVERY)
    rss_samples, finish = leg("endurance_phase_a_crash")
    windows = edges = 0
    for res in drv.stream_file(fixture):
        windows += 1
        edges += res.num_edges
        if windows % 16 == 0:
            rss_samples.append(rss_mb())
        if windows >= crash_at:
            break      # simulated crash: abandon mid-iteration
    finish(drv, windows, edges, tail_compiles=-1)
    del drv

    # ---- phase B: fresh driver, resume from the newest checkpoint,
    # steady-state tail must be compile-free (buckets stopped growing
    # long before the crash point: V_END << edges at 50%)
    drv = StreamingAnalyticsDriver(window_ms=1000)
    assert drv.try_resume(ckpt), "checkpoint did not restore"
    resumed_at = drv.windows_done
    assert resumed_at <= crash_at, (resumed_at, crash_at)
    # lag bound: one checkpoint interval plus one scan chunk (staging
    # happens at scan-chunk boundaries; driver._stage_ckpt)
    assert resumed_at >= crash_at - CKPT_EVERY - drv._SCAN_CHUNK, (
        resumed_at, crash_at)
    drv.enable_auto_checkpoint(ckpt, every_n_windows=CKPT_EVERY)
    rss_samples, finish = leg("endurance_phase_b_resume")
    windows = edges = 0
    tail_from = (total_windows * 3) // 4
    tail_compiles = 0
    seen_events = len(counter.events)
    deg_sum = None
    mem_samples = []  # per-round memory gauges (the leak detector)
    for res in drv.stream_file(fixture, resume=True):
        windows += 1
        edges += res.num_edges
        if windows % 16 == 0:
            rss_samples.append(rss_mb())
            mem_samples.append(metrics.sample_memory())
        new = len(counter.events) - seen_events
        seen_events = len(counter.events)
        if drv.windows_done > tail_from and new:
            tail_compiles += new
        deg_sum = res.degrees
    row = finish(drv, windows, edges, tail_compiles)
    # memory-gauge leg: fail the soak on monotonic live-buffer growth
    rows.append(check_buffer_leak(mem_samples))
    print(json.dumps(rows[-1]), flush=True)

    # ---- invariants: nothing dropped, nothing double-counted
    assert drv.windows_done == total_windows, (
        drv.windows_done, total_windows)
    assert drv.edges_done == NUM_EDGES, (drv.edges_done, NUM_EDGES)
    assert int(deg_sum.sum()) == 2 * NUM_EDGES, (
        int(deg_sum.sum()), 2 * NUM_EDGES)
    assert row["compiles_steady_state_tail"] == 0, row
    # bounded memory: the post-warmup ceiling is flat (max within 20%
    # of the p10 once past the first quarter of phase B)
    assert row["rss_mb_max"] <= 1.2 * row["rss_mb_p10"] + 512, row
    rows.append({"leg": "endurance_invariants", "ok": True,
                 "total_windows": total_windows,
                 "resumed_at_window": resumed_at,
                 "crash_at_window": crash_at})
    print(json.dumps(rows[-1]), flush=True)
    with open(out_path, "w") as f:
        json.dump(rows, f, indent=1)


def main():
    import argparse

    ap = argparse.ArgumentParser()
    # size-keyed default: the run streams the WHOLE fixture file, so a
    # larger cached fixture from a previous (e.g. 100M) run would make
    # a scaled-down GS_END_EDGES rerun process the big stream and fail
    # its total-window asserts
    ap.add_argument("--fixture",
                    default="/tmp/gs_endurance_%d.txt" % NUM_EDGES)
    ap.add_argument("--out", default=os.path.join(
        REPO, "ENDURANCE_r05.json"))
    args = ap.parse_args()
    # regenerate when missing, too small, OR far larger than this
    # run expects: the tool streams the WHOLE file, so an oversized
    # cached fixture (e.g. a 100M-edge file passed explicitly to a
    # scaled-down run) would fail the total-window asserts hours in
    size = (os.path.getsize(args.fixture)
            if os.path.exists(args.fixture) else 0)
    if not (NUM_EDGES * 10 <= size <= NUM_EDGES * 40):
        generate(args.fixture)
    run(args.fixture, args.out)


if __name__ == "__main__":
    main()

"""Replay one (or every) provenance-ledger window and diff digests —
the migration/rebalance parity oracle as an operator command.

Given a provenance record (utils/provenance.py: tenant, window
ordinal, covered `wal_offset` span, tier, program, summary sha256),
this tool re-derives the window from first principles:

  1. load the nearest per-tenant checkpoint at or before the record's
     `wal_lo` (cohort layout: `<ckpt-dir>/tenant_<id>.npz`, rotation
     handled by utils/checkpoint.load_latest) — or start from a fresh
     engine at offset 0 when none exists,
  2. replay the WAL strictly across [checkpoint offset, wal_hi)
     (utils/wal.replay trims to the exact boundary),
  3. recompute on a CHOSEN tier — the host twin by default
     (parallel/host_twin.HostSummaryEngine /
     ops/gnn_window.GnnHostEngine: no compiler, no device), or the
     fused scan tier (`--tier scan`) for a cross-tier check,
  4. diff the recomputed summary's sha256 against the record's.

A digest match proves the ledger record, the WAL span, and the
checkpoint lineage agree bit-for-bit — the proof a fleet router needs
before (and after) moving a tenant between hosts. Records this tool
cannot replay are reported with an explicit reason, never silently
skipped (tools/provenance_smoke.py turns a skip into a CI failure;
`program=driver` records carry WindowResult-array digests and are
verified by the driver's own kill→replay re-emission instead —
tests/test_provenance.py).

Usage:
  python -m tools.replay_window --prov-dir DIR --wal-dir DIR \
      [--ckpt-dir DIR] [--tenant T] [--window N] [--tier host|scan] \
      [--eb N --vb N] [--json]

Exit status: 0 = every selected record verified, 1 = any mismatch or
unreplayable record.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from gelly_streaming_tpu.utils import checkpoint  # noqa: E402
from gelly_streaming_tpu.utils import provenance  # noqa: E402
from gelly_streaming_tpu.utils import wal as wal_mod  # noqa: E402

_GNN_PROGRAMS = ("gnn_round",)


def _safe_tid(tid: str) -> str:
    # mirror of core/tenancy.TenantCohort._ckpt_path's sanitization
    return "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in str(tid))


def load_records(prov_dir: str, tenant=None, window=None, tier=None):
    """The ledger's records, optionally filtered; duplicates for the
    same (tenant, window, tier) key are collapsed to the LAST record
    (at-least-once re-emission after a crash is expected and benign —
    byte-identical by the ledger contract, pinned by tests)."""
    sc = provenance.scan(prov_dir)
    keyed = {}
    for rec in sc["records"]:
        if tenant is not None and rec["tenant"] != str(tenant):
            continue
        if window is not None and rec["window"] != int(window):
            continue
        if tier is not None and rec["tier"] != tier:
            continue
        keyed[(rec["tenant"], rec["window"], rec["tier"])] = rec
    return [keyed[k] for k in sorted(keyed)], sc["torn"]


def collect_span(wal_dir: str, tenant: str, lo: int, hi: int,
                 clamp: bool = False):
    """The tenant's journaled edges across [lo, hi) as (src, dst), or
    None when the journal no longer covers the span (retention
    truncated it and no checkpoint bridges the gap). `clamp=True`
    accepts a journal that ends inside the span (serve-tier records
    carry the NOMINAL eb-aligned window span, so a closed tenant's
    short final window legitimately falls short of `hi`)."""
    src_parts, dst_parts = [], []
    have = lo
    for tid, start, s, d, _ts in wal_mod.replay(wal_dir, {tenant: lo}):
        if tid != tenant:
            continue
        if start > have:
            return None  # a truncated prefix left a hole in the span
        take = min(len(s), hi - have)
        src_parts.append(s[:take])
        dst_parts.append(d[:take])
        have += take
        if have >= hi:
            break
    if have < hi and not (clamp and have > lo):
        return None
    if not src_parts:
        return (np.zeros(0, np.int64), np.zeros(0, np.int64))
    return (np.concatenate(src_parts), np.concatenate(dst_parts))


def _load_ckpt(ckpt, tenant):
    """Resolve `ckpt` (None | state dict | .npz path | cohort ckpt
    dir) to a tenant state dict or None."""
    if ckpt is None:
        return None
    if isinstance(ckpt, dict):
        return ckpt
    path = ckpt
    if os.path.isdir(path):
        path = os.path.join(path, "tenant_%s.npz" % _safe_tid(tenant))
    got = checkpoint.load_latest(path)
    return got[0] if got is not None else None


def _build_engine(rec, state, tier, eb, vb, kb):
    """The replay engine for a record's family on the chosen tier —
    (engine, start_offset) or (None, reason)."""
    gnn = rec["program"] in _GNN_PROGRAMS
    if state is not None:
        if gnn:
            from gelly_streaming_tpu.ops import gnn_window
            cls = (gnn_window.GnnHostEngine if tier == "host"
                   else gnn_window.GnnSummaryEngine)
            eng = cls.from_state(state)
        else:
            from gelly_streaming_tpu.ops import scan_analytics
            from gelly_streaming_tpu.parallel import host_twin
            if tier == "host":
                eng = host_twin.HostSummaryEngine.from_state(state)
            else:
                eng = scan_analytics.StreamSummaryEngine(
                    edge_bucket=int(state["edge_bucket"]),
                    vertex_bucket=int(state["vertex_bucket"]))
                eng.load_state_dict(state)
        return eng, int(state["wal_offset"])
    if gnn:
        # a fresh GNN engine has no layer weights — the checkpoint IS
        # the weight source, so replay without one cannot be faithful
        return None, "gnn record needs a checkpoint (layer weights)"
    if not eb or not vb:
        return None, ("no checkpoint found: pass --eb/--vb to replay "
                      "from a fresh engine at offset 0")
    from gelly_streaming_tpu.parallel import host_twin
    from gelly_streaming_tpu.ops import scan_analytics
    if tier == "host":
        eng = host_twin.HostSummaryEngine(edge_bucket=eb,
                                          vertex_bucket=vb)
    else:
        eng = scan_analytics.StreamSummaryEngine(
            edge_bucket=eb, vertex_bucket=vb, k_bucket=kb)
    return eng, 0


def replay_record(rec, wal_dir, ckpt=None, tier="host",
                  eb=None, vb=None, kb=0) -> dict:
    """Replay ONE provenance record; returns a verdict row:
    {"tenant", "window", "tier", "replay_tier", "ok", "recorded",
     "computed", "skipped"} — `skipped` holds the reason a record
    could not be replayed (and `ok` is False), so no record ever
    disappears from the report."""
    row = {"tenant": rec["tenant"], "window": rec["window"],
           "tier": rec["tier"], "replay_tier": tier, "ok": False,
           "recorded": rec["digest"], "computed": None,
           "skipped": None}
    if rec["program"] == "driver":
        row["skipped"] = ("driver records digest WindowResult arrays; "
                          "verify via the driver's kill->replay "
                          "re-emission (tests/test_provenance.py)")
        return row
    state = _load_ckpt(ckpt, rec["tenant"])
    if state is not None and (
            int(state["wal_offset"]) > int(rec["wal_lo"])):
        # the checkpoint is AHEAD of this (older) record: a fresh
        # engine from offset 0 is the only faithful lineage left
        state = None
    eng, start = _build_engine(rec, state, tier, eb, vb, kb)
    if eng is None:
        row["skipped"] = start
        return row
    span = collect_span(wal_dir, rec["tenant"], start,
                        int(rec["wal_hi"]),
                        clamp=rec["program"] == "serve")
    if span is None:
        row["skipped"] = ("WAL no longer covers [%d, %d) for this "
                          "tenant (retention?)"
                          % (start, int(rec["wal_hi"])))
        return row
    # a replay is an audit READ: the recompute engine is itself a
    # finalize owner, so disarm the ledger around it or every replay
    # would append fresh records to the ledger it is auditing
    prev = os.environ.get("GS_PROVENANCE")
    os.environ["GS_PROVENANCE"] = "0"
    try:
        summaries = eng.process(*span)
    finally:
        if prev is None:
            os.environ.pop("GS_PROVENANCE", None)
        else:
            os.environ["GS_PROVENANCE"] = prev
    idx = int(rec["window"]) - start // eng.eb
    if not 0 <= idx < len(summaries):
        row["skipped"] = ("replay produced %d windows from offset %d; "
                          "ordinal %d is out of range"
                          % (len(summaries), start, rec["window"]))
        return row
    row["computed"] = provenance.summary_digest(summaries[idx])
    row["ok"] = row["computed"] == rec["digest"]
    return row


def replay_all(prov_dir, wal_dir, ckpt=None, tier="host", eb=None,
               vb=None, kb=0, tenant=None, window=None,
               rec_tier=None) -> dict:
    recs, torn = load_records(prov_dir, tenant=tenant, window=window,
                              tier=rec_tier)
    rows = [replay_record(r, wal_dir, ckpt=ckpt, tier=tier, eb=eb,
                          vb=vb, kb=kb) for r in recs]
    return {
        "records": len(recs),
        "verified": sum(1 for r in rows if r["ok"]),
        "mismatched": sum(1 for r in rows
                          if not r["ok"] and r["skipped"] is None),
        "skipped": sum(1 for r in rows if r["skipped"] is not None),
        "torn": torn,
        "knob_fingerprint": provenance.knob_fingerprint(),
        "rows": rows,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="replay provenance-ledger windows and diff "
                    "digests (the tenant-migration parity oracle)")
    ap.add_argument("--prov-dir", required=True,
                    help="provenance ledger directory (prov_*.seg)")
    ap.add_argument("--wal-dir", required=True,
                    help="WAL journal directory (wal_*.seg)")
    ap.add_argument("--ckpt-dir", default=None,
                    help="per-tenant checkpoint dir (tenant_<id>.npz) "
                         "or one checkpoint .npz path")
    ap.add_argument("--tenant", default=None,
                    help="only this tenant's records")
    ap.add_argument("--window", type=int, default=None,
                    help="only this window ordinal")
    ap.add_argument("--record-tier", default=None,
                    help="only records emitted by this tier")
    ap.add_argument("--tier", default="host",
                    choices=("host", "scan"),
                    help="tier to recompute on (default: host twin)")
    ap.add_argument("--eb", type=int, default=None,
                    help="edge bucket for fresh-engine replay (no "
                         "checkpoint)")
    ap.add_argument("--vb", type=int, default=None,
                    help="vertex bucket for fresh-engine replay")
    ap.add_argument("--kb", type=int, default=0,
                    help="K bucket for fresh-engine replay")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    args = ap.parse_args(argv)

    rep = replay_all(args.prov_dir, args.wal_dir, ckpt=args.ckpt_dir,
                     tier=args.tier, eb=args.eb, vb=args.vb,
                     kb=args.kb, tenant=args.tenant,
                     window=args.window, rec_tier=args.record_tier)
    if args.json:
        print(json.dumps(rep, indent=2, sort_keys=True))
    else:
        for r in rep["rows"]:
            state = ("OK" if r["ok"] else
                     "SKIP (%s)" % r["skipped"] if r["skipped"]
                     else "MISMATCH")
            print("%-12s w%-6d %-14s -> %-4s  %s"
                  % (r["tenant"], r["window"], r["tier"],
                     r["replay_tier"], state))
        print("replayed %d record(s): %d verified, %d mismatched, "
              "%d skipped%s"
              % (rep["records"], rep["verified"], rep["mismatched"],
                 rep["skipped"],
                 "" if not rep["torn"] else
                 " [torn tail: %s]" % rep["torn"]["problem"]))
    bad = rep["mismatched"] + rep["skipped"]
    return 1 if bad or not rep["records"] else 0


if __name__ == "__main__":
    sys.exit(main())

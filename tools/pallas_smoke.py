#!/usr/bin/env python
"""CI gate [7/7]: interpret-mode megakernel smoke.

One window through StreamSummaryEngine with the fused Pallas window
megakernel pinned ON (interpret mode on the CPU backend) must be
digest-identical to the XLA fused scan — so the static gate catches
Pallas API drift (a jax upgrade changing pallas_call's contract, a
broken kernel edit) without a chip, the same way gate 5 pins the
cohort to the single-stream digest. Exits non-zero on digest
mismatch OR if the megakernel was not actually selected (a silently
refused probe would otherwise let the gate pass while testing
nothing).

Usage: JAX_PLATFORMS=cpu python tools/pallas_smoke.py
"""

import hashlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def _digest(summaries) -> str:
    h = hashlib.sha256()
    for s in summaries:
        h.update(json.dumps(s, sort_keys=True).encode())
    return h.hexdigest()[:16]


def main() -> int:
    os.environ.setdefault("GS_AUTOTUNE", "0")
    from gelly_streaming_tpu.ops import pallas_window as pw
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    eb = vb = 256
    rng = np.random.default_rng(42)
    src = rng.integers(0, vb - 8, eb).astype(np.int32)
    dst = rng.integers(0, vb - 8, eb).astype(np.int32)

    os.environ["GS_PALLAS_WINDOW"] = "off"
    pw._reset_pallas_window()
    ref = StreamSummaryEngine(edge_bucket=eb,
                              vertex_bucket=vb).process(src, dst)

    os.environ["GS_PALLAS_WINDOW"] = "on"
    pw._reset_pallas_window()
    eng = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    if not eng._pallas:
        print("pallas_smoke: megakernel NOT selected under "
              "GS_PALLAS_WINDOW=on (build/trace probe refused — see "
              "the durable selection.fallback event)")
        return 1
    got = eng.process(src, dst)

    dr, dg = _digest(ref), _digest(got)
    if dr != dg:
        print("pallas_smoke: DIGEST MISMATCH megakernel %s != xla %s"
              % (dg, dr))
        print("xla: %s" % json.dumps(ref))
        print("pallas: %s" % json.dumps(got))
        return 1
    print("pallas_smoke: ok (1 window, digest %s, megakernel ≡ XLA "
          "fused scan)" % dr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Serving front-end parity smoke — the CI gate (tools/ci_check.sh).

One tenant, fed through a real loopback socket into a journal-armed
`core/serve.StreamServer`, must produce the BYTE-IDENTICAL summary
digest of the same stream fed directly into a `TenantCohort` — the
wire protocol, the admission path, the write-ahead journal, and the
drain can never change results, only availability.

Checks, in order:
  1. loopback digest == direct-feed digest (the serve path is a
     transparent transport);
  2. drain() finalizes every queued window (drain digest == the
     keep-running digest) and leaves a SEALED journal;
  3. the journal's recorded edge count equals what was fed.

Exit 0 = clean. Runs in seconds on the CPU backend.
"""

import hashlib
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from bench import make_stream  # noqa: E402
from gelly_streaming_tpu.core.serve import (  # noqa: E402
    ServeClient, StreamServer)
from gelly_streaming_tpu.core.tenancy import TenantCohort  # noqa: E402
from gelly_streaming_tpu.utils import wal  # noqa: E402


def digest_summaries(summaries) -> str:
    h = hashlib.sha256()
    for s in summaries:
        h.update(json.dumps(s, sort_keys=True).encode())
    return h.hexdigest()[:16]


def main() -> int:
    eb, vb, num_w = 512, 1024, 6
    src, dst = make_stream(num_w * eb, vb, seed=7)
    src = np.asarray(src, np.int32)
    dst = np.asarray(dst, np.int32)

    direct = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    direct.admit("t1")
    oracle = []
    for i in range(num_w):
        direct.feed("t1", src[i * eb:(i + 1) * eb],
                    dst[i * eb:(i + 1) * eb])
        oracle += direct.pump().get("t1", [])
    oracle += direct.close("t1")
    want = digest_summaries(oracle)

    with tempfile.TemporaryDirectory(prefix="gs-serve-smoke-") as wd:
        wal_dir = os.path.join(wd, "wal")
        cohort = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        cohort.enable_wal(wal_dir)
        cohort.enable_auto_checkpoint(os.path.join(wd, "ckpt"),
                                      every_n_windows=2)
        server = StreamServer(cohort, port=0).start()
        cli = ServeClient(server.port)
        got = []
        try:
            assert cli.admit("t1")["ok"]
            # hold the last window queued so drain() must finalize it
            for i in range(num_w):
                r = cli.feed("t1", src[i * eb:(i + 1) * eb],
                             dst[i * eb:(i + 1) * eb])
                if not r.get("ok"):
                    print("serve smoke FAILED: feed rejected: %s" % r)
                    return 1
                if i < num_w - 1:
                    got += [row["summary"] for row in
                            cli.pump()["results"].get("t1", [])]
        finally:
            cli.close()
        drain = server.drain(deadline_s=5)
        # the authoritative stream is the server's results sink
        # (drain finalized the held-back windows into it)
        got = [row["summary"] for row in server.results["t1"]]
        server.close()
        if drain["drained_windows"] < 1:
            print("serve smoke FAILED: drain finalized no queued "
                  "window (%s)" % drain)
            return 1
        info = wal.scan(wal_dir)
        if not info["sealed"] or info["offsets"].get("t1") \
                != num_w * eb:
            print("serve smoke FAILED: journal not sealed/complete: "
                  "%s" % info)
            return 1
    have = digest_summaries(got)
    if have != want or len(got) != len(oracle):
        print("serve smoke FAILED: loopback digest %s (%d windows) "
              "!= direct %s (%d windows)"
              % (have, len(got), want, len(oracle)))
        return 1
    print("serve smoke ok: loopback+drain ≡ direct feed (%s, "
          "%d windows, sealed journal)" % (want, len(got)))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Perf regression sentry: compare a bench/profile run against a
committed baseline and exit non-zero on regression — the automated
"did this PR make it slower" answer for CI and the chip window.

Baselines and current runs may be any of:

  - a committed `BENCH_r*.json` capture ({"tail": ..., "parsed": ...}
    — every JSON metric line in the tail is a row),
  - a raw `python bench.py` stdout capture (one JSON object per line),
  - a `PERF*.json` evidence file (rows are pulled from the sections
    that carry throughput numbers: host_stream / host_snapshot /
    host_reduce / pipeline_stages / ingress_ab / egress_ab /
    telemetry_meta / metrics).

Rows are matched by their stable identity (the bench `metric` string,
or section + probe/bucket keys), and every shared throughput field
(`value`, `*_edges_per_s`) plus `pipeline_speedup` / `speedup` /
`vs_baseline` is compared: current/baseline below `1 - tolerance` is
a regression. Latency identities invert: every shared
`*_p{50,95,99}_s` field (bench serving rows, the PERF `latency`
section) regresses when current/baseline EXCEEDS `1 + tolerance` —
lower is better there. The bench rows on this host historically swing with
load (bench.py medians exist for that reason), so the default
tolerance is deliberately wide (--tolerance 0.2 = flag >20% drops);
CI that controls its host can tighten it.

Output: a JSON report whose `regressions` section is schema-validated
(tools/perf_schema.py) before it is written — a malformed sentry
report must fail the sentry, not the consumer. Exit status: 0 clean,
1 regressions found, 2 usage/IO errors.

Usage:
  python tools/bench_compare.py --baseline BENCH_r05.json \
         [--current RUN.jsonl] [--tolerance 0.2] [--out REPORT.json]

With no --current the baseline is compared against itself — a wiring
smoke check that must always exit 0.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# fields compared when present in BOTH rows: absolute-throughput
# fields (higher is better) and ratio fields (higher is better)
RATE_FIELDS = (
    "value", "sync_prep_edges_per_s", "device_path_edges_per_s",
    "baseline_cpu_edges_per_s", "host_edges_per_s",
    "device_edges_per_s", "native_edges_per_s", "scan_edges_per_s",
    "pipelined_edges_per_s", "sync_edges_per_s", "std_edges_per_s",
    "compact_edges_per_s", "full_edges_per_s", "delta_edges_per_s",
    "armed_edges_per_s", "disarmed_edges_per_s", "edges_per_s",
    "resident_edges_per_s", "perwindow_edges_per_s",
    "tenant_edges_per_s", "sequential_edges_per_s",
    "gnn_edge_features_per_s", "cohort_edges_per_s",
)
RATIO_FIELDS = ("pipeline_speedup", "speedup", "vs_baseline",
                "cohort_speedup", "queue_wait_improvement",
                "e2e_improvement")

# latency identities (LOWER is better — the comparison inverts):
# any field both rows share whose name ends in a percentile-seconds
# suffix is compared as current/baseline ABOVE 1 + tolerance = a
# latency regression. bench.py serving rows emit serve_e2e_p{50,95,
# 99}_s and PERF latency sections emit e2e_p{50,95,99}_s.
LATENCY_SUFFIXES = ("_p50_s", "_p95_s", "_p99_s")

# robustness counters (LOWER is better, zero is the healthy state):
# rejected-record and quarantine totals a clean serving run must keep
# at 0 — a baseline-0 counter that turns non-zero is a regression
# regardless of ratio, and a non-zero baseline regresses past
# 1 + tolerance like the latency identities
COUNTER_FIELDS = ("dlq_records", "quarantines")

# PERF.json sections that carry comparable rows, with the keys that
# identify a row within the section
PERF_SECTIONS = {
    "host_stream": ("edge_bucket",),
    "host_snapshot": ("edge_bucket",),
    "host_reduce": ("edge_bucket", "name"),
    "pipeline_stages": ("engine", "edge_bucket"),
    "ingress_ab": ("probe",),
    "egress_ab": ("probe",),
    "resident_ab": ("probe",),
    "tenancy_ab": ("probe", "tenants"),
    "pump_ab": ("probe",),
    "gnn_ab": ("probe", "tenants"),
    "autotune": ("engine", "edge_bucket"),
}


def _json_lines(text: str) -> list:
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            row = json.loads(line)
        except ValueError:
            continue
        if isinstance(row, dict):
            rows.append(row)
    return rows


def extract_rows(doc, label: str) -> dict:
    """{row identity → row dict} from any supported shape."""
    out = {}

    def add(key, row):
        # duplicate identities (a re-run scale): last wins, matching
        # bench.py's the-last-line-wins convention
        out[key] = row

    # identity fields that are present-but-null are treated exactly
    # like missing ones: a row {"metric": null} must neither create a
    # phantom `None` identity nor match differently than a row that
    # simply lacks the key (pinned by test_perf_tooling)
    if isinstance(doc, str):
        for row in _json_lines(doc):
            if row.get("metric") is not None:
                add(row["metric"], row)
        return out
    if not isinstance(doc, dict):
        raise ValueError("%s: unsupported document shape %s"
                         % (label, type(doc).__name__))
    if "tail" in doc and isinstance(doc.get("tail"), str):
        # committed BENCH_r*.json capture
        for row in _json_lines(doc["tail"]):
            if row.get("metric") is not None:
                add(row["metric"], row)
        parsed = doc.get("parsed")
        if isinstance(parsed, dict) \
                and parsed.get("metric") is not None:
            add(parsed["metric"], parsed)
        return out
    if doc.get("metric") is not None:
        add(doc["metric"], doc)
        return out
    # PERF*.json evidence file
    for section, keys in PERF_SECTIONS.items():
        rows = doc.get(section)
        if not isinstance(rows, list):
            continue
        for row in rows:
            if not isinstance(row, dict):
                continue
            ident = "%s[%s]" % (section, ",".join(
                str(row.get(k)) for k in keys))
            add(ident, row)
    for meta_key in ("telemetry_meta", "metrics", "latency",
                     "sanitize", "provenance"):
        meta = doc.get(meta_key)
        if isinstance(meta, dict):
            add(meta_key, meta)
    return out


def row_trace(row) -> str:
    """The run trace ID one row carries (bench rows stamp `trace`,
    armed ones nest it under `telemetry` too); None when absent."""
    if not isinstance(row, dict):
        return None
    t = row.get("trace")
    if isinstance(t, str) and t:
        return t
    tel = row.get("telemetry")
    if isinstance(tel, dict) and isinstance(tel.get("trace"), str):
        return tel["trace"]
    return None


def doc_trace(rows: dict) -> str:
    """The first run trace ID any of a document's rows carries — the
    correlation key that links a sentry regression to the ledger
    tools/explain_perf.py drills into. None when no row carries one."""
    for row in rows.values():
        t = row_trace(row)
        if t:
            return t
    return None


def load_rows(path: str) -> dict:
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = text  # raw bench stdout: one JSON object per line
    rows = extract_rows(doc, path)
    if not rows:
        raise ValueError(
            "%s: no comparable rows found (expected bench JSON lines, "
            "a BENCH_r*.json capture, or a PERF*.json file)" % path)
    return rows


def compare(base_rows: dict, cur_rows: dict, tolerance: float) -> dict:
    """The sentry verdict: per-row field comparisons plus the
    schema-validated `regressions` section."""
    compared, regressions, skipped = [], [], []
    for ident in sorted(base_rows):
        if ident not in cur_rows:
            skipped.append(ident)
            continue
        b, c = base_rows[ident], cur_rows[ident]
        for field in RATE_FIELDS + RATIO_FIELDS:
            bv, cv = b.get(field), c.get(field)
            if not isinstance(bv, (int, float)) \
                    or not isinstance(cv, (int, float)) \
                    or isinstance(bv, bool) or isinstance(cv, bool) \
                    or bv <= 0:
                continue
            ratio = cv / bv
            row = {"row": ident, "field": field,
                   "baseline": bv, "current": cv,
                   "ratio": round(ratio, 4)}
            compared.append(row)
            if ratio < 1.0 - tolerance:
                regressions.append(dict(row, tolerance=tolerance))
        # robustness counters: lower is better, and a clean (0)
        # baseline turning non-zero is a regression outright — there
        # is no ratio that makes new rejected records acceptable
        for field in COUNTER_FIELDS:
            bv, cv = b.get(field), c.get(field)
            if not isinstance(bv, (int, float)) \
                    or not isinstance(cv, (int, float)) \
                    or isinstance(bv, bool) or isinstance(cv, bool):
                continue
            ratio = (cv / bv) if bv > 0 else float(cv)
            row = {"row": ident, "field": field,
                   "baseline": bv, "current": cv,
                   "ratio": round(ratio, 4),
                   "direction": "lower_is_better"}
            compared.append(row)
            if (bv == 0 and cv > 0) \
                    or (bv > 0 and ratio > 1.0 + tolerance):
                regressions.append(dict(row, tolerance=tolerance))
        # latency identities: every shared *_p{50,95,99}_s field,
        # compared inverted (LOWER is better — current/baseline past
        # 1 + tolerance is the regression)
        for field in sorted(k for k in b
                            if isinstance(k, str)
                            and k.endswith(LATENCY_SUFFIXES)):
            bv, cv = b.get(field), c.get(field)
            if not isinstance(bv, (int, float)) \
                    or not isinstance(cv, (int, float)) \
                    or isinstance(bv, bool) or isinstance(cv, bool) \
                    or bv <= 0:
                continue
            ratio = cv / bv
            row = {"row": ident, "field": field,
                   "baseline": bv, "current": cv,
                   "ratio": round(ratio, 4),
                   "direction": "lower_is_better"}
            compared.append(row)
            if ratio > 1.0 + tolerance:
                regressions.append(dict(row, tolerance=tolerance))
    return {
        "backend": "bench_compare",
        "tolerance": tolerance,
        "rows_compared": len({r["row"] for r in compared}),
        "fields_compared": len(compared),
        "rows_only_in_baseline": skipped,
        "comparisons": compared,
        "regressions": regressions,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", required=True,
                    help="committed baseline (BENCH_r*.json, bench "
                         "stdout, or PERF*.json)")
    ap.add_argument("--current", default=None,
                    help="current run in any supported shape; omitted "
                         "= self-compare the baseline (smoke mode, "
                         "always exit 0)")
    ap.add_argument("--tolerance", type=float, default=0.2,
                    help="relative drop that counts as a regression "
                         "(default 0.2 = 20%%)")
    ap.add_argument("--out", default=None,
                    help="write the JSON report here")
    args = ap.parse_args(argv)
    if not 0 < args.tolerance < 1:
        print("bench_compare: --tolerance must be in (0, 1)",
              file=sys.stderr)
        return 2

    try:
        base_rows = load_rows(args.baseline)
        cur_rows = (load_rows(args.current)
                    if args.current else dict(base_rows))
    except (OSError, ValueError) as e:
        print("bench_compare: %s" % e, file=sys.stderr)
        return 2
    if args.current is None:
        print("bench_compare: no --current given — self-comparing "
              "the baseline (smoke mode)", file=sys.stderr)

    report = compare(base_rows, cur_rows, args.tolerance)
    report["baseline_path"] = args.baseline
    report["current_path"] = args.current or args.baseline
    # trace-ID correlation: a non-zero exit should link straight to
    # its attributed cause — stamp the run trace IDs the rows carry
    # so `tools/explain_perf.py --regression <report>` can find the
    # right ledger without guesswork
    base_trace, cur_trace = doc_trace(base_rows), doc_trace(cur_rows)
    if base_trace:
        report["baseline_trace"] = base_trace
    if cur_trace:
        report["current_trace"] = cur_trace
    for r in report["regressions"]:
        # per-row first: a file accumulated across several runs holds
        # several trace IDs, and the drill-down must follow the
        # REGRESSING row's run, not whichever row was seen first
        bt = row_trace(base_rows.get(r["row"])) or base_trace
        ct = row_trace(cur_rows.get(r["row"])) or cur_trace
        if bt:
            r["baseline_trace"] = bt
        if ct:
            r["current_trace"] = ct

    # the sentry's own output contract: a malformed `regressions`
    # section must fail HERE, not in a CI consumer
    from tools import perf_schema

    problems = perf_schema.validate(report)
    if problems:
        print("bench_compare: internal schema violation:\n  %s"
              % "\n  ".join(problems), file=sys.stderr)
        return 2

    print(json.dumps(report, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2)
        print("wrote %s" % args.out, file=sys.stderr)
    if report["regressions"]:
        for r in report["regressions"]:
            if r.get("direction") == "lower_is_better":
                print("REGRESSION %s.%s: %s -> %s (x%.3f > 1+%.2f, "
                      "latency)" % (r["row"], r["field"],
                                    r["baseline"], r["current"],
                                    r["ratio"], args.tolerance),
                      file=sys.stderr)
                continue
            print("REGRESSION %s.%s: %s -> %s (x%.3f < 1-%.2f)"
                  % (r["row"], r["field"], r["baseline"], r["current"],
                     r["ratio"], args.tolerance), file=sys.stderr)
        if args.out:
            print("drill down: python tools/explain_perf.py "
                  "--regression %s" % args.out, file=sys.stderr)
        return 1
    if not report["fields_compared"]:
        print("bench_compare: no overlapping rows/fields to compare",
              file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Answer "where did the time go" for a streaming run: the
drill-down that joins the flight recorder's ledger (utils/telemetry),
the program cost observatory (utils/costmodel, PERF.json `cost_model`)
and, optionally, a bench_compare regression report into one
attribution verdict:

  - **per-stage attribution**: the ledger's leaf stage spans (prep /
    h2d / dispatch / d2h+finalize / checkpoint, `other` for anything
    unmapped) summed per stage. Container spans are excluded
    STRUCTURALLY — any span that parents another span double-books
    its children's time, whatever it is named — with the known
    envelope names as a fallback for ledgers without parent links.
    The conservation check is on the mapped fraction: leaf time the
    stage taxonomy could NOT name (`other`) beyond `--tolerance`
    (default 5%) of the ledger's leaf-span total exits non-zero,
    naming the unmapped spans — a new span name can't silently
    vanish from the attribution;
  - **per-program attribution**: dispatch spans tagged program/sig
    (the cost observatory stamps them) joined with the cost
    registry's FLOPs/bytes → achieved-vs-roofline fraction and the
    bytes/FLOPs boundedness verdict per program per shape; each
    chunk-correlated finalize span is attributed to its chunk's
    program as materialize (d2h) time;
  - **ranked suspects**: deterministic heuristics over the above —
    recompile storm (durable events in the ledger), host-sync /
    d2h-bound (finalize-stage fraction), launch-bound (measured
    dispatch ≫ roofline-implied seconds), bytes-bound (the cost
    verdict where it dominates), prep-bound (host prep fraction).

Usage:
  python tools/explain_perf.py [--ledger L.jsonl] [--perf PERF_cpu.json]
        [--trace-id ID] [--regression REPORT.json] [--json]
        [--tolerance 0.05] [--top N]

With only --perf, the ledger is resolved from the committed
`cost_model` section (the profiler commits its attribution ledger
beside the rows). With --regression (a bench_compare --out report),
the regression rows and their trace IDs are printed first, so a
sentry's non-zero exit links directly to its attributed cause.

Exit status: 0 attributed; 1 no usable records OR the stage table
fails conservation; 2 usage/IO errors.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import importlib.util as _ilu  # noqa: E402


def _load_tool(name):
    spec = _ilu.spec_from_file_location(
        name, os.path.join(REPO, "tools", name + ".py"))
    mod = _ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


trace_report = _load_tool("trace_report")

# leaf stage spans → attribution stage. Container spans (chunk/round
# envelopes that ENCLOSE leaves) are excluded from totals entirely —
# counting both would double-book every second.
STAGE_OF = {
    "ingress.prep": "prep",
    "step.intern": "prep",
    "ingress.h2d": "h2d",
    "ingress.dispatch": "dispatch",
    "step.snapshot_scan": "dispatch",
    "step.triangles": "dispatch",
    "ingress.finalize": "d2h+finalize",
    "step.snapshot_wait": "d2h+finalize",
    "step.checkpoint": "checkpoint",
}
CONTAINERS = {
    "ingress.chunk", "fused_scan.round", "triangles.round",
    "reduce.stream", "driver.scan_round", "resident.superbatch",
    "sharded.stream", "sharded.window",
}
STAGE_ORDER = ("prep", "h2d", "dispatch", "d2h+finalize",
               "checkpoint", "other")


def leaf_spans(records):
    """Span records minus containers. A container is detected
    STRUCTURALLY — its span id is some other span's parent (keyed per
    trace: sids restart per recorder), so an envelope the CONTAINERS
    list doesn't know yet still can't double-book its children — with
    the known names as a fallback for ledgers without parent links."""
    spans = [r for r in records if r.get("t") == "span"]
    parents = {(r.get("trace"), r["par"])
               for r in spans if r.get("par") is not None}
    return [r for r in spans
            if r.get("name") not in CONTAINERS
            and (r.get("trace"), r.get("sid")) not in parents]


def stage_attribution(records):
    """Per-stage totals over the ledger's leaf spans, plus the
    conservation numbers: the attributed total, the independent
    leaf-span total (via trace_report's own accounting), and the
    `other` rows' unmapped span names — main() fails the run when
    the taxonomy couldn't name more than --tolerance of the time."""
    totals = {s: {"stage": s, "count": 0, "total_s": 0.0}
              for s in STAGE_ORDER}
    unmapped = {}
    for rec in leaf_spans(records):
        stage = STAGE_OF.get(rec.get("name"), "other")
        totals[stage]["count"] += 1
        totals[stage]["total_s"] += float(rec.get("dur", 0.0))
        if stage == "other":
            unmapped[rec.get("name")] = unmapped.get(
                rec.get("name"), 0) + 1
    # independent accounting through trace_report's own per-span rows
    # (same records, different code path — the cross-check)
    ledger_total = sum(r["total_ms"] for r in trace_report.span_rows(
        leaf_spans(records))) / 1e3
    attributed = sum(t["total_s"] for t in totals.values())
    rows = [dict(t, total_s=round(t["total_s"], 6),
                 frac=round(t["total_s"] / attributed, 4)
                 if attributed else 0.0)
            for t in (totals[s] for s in STAGE_ORDER) if t["count"]]
    return rows, round(attributed, 6), round(ledger_total, 6), \
        sorted(unmapped)


def program_attribution(records, cost_rows):
    """Per-(program, sig) measured economics joined with the cost
    registry: dispatch spans tagged by the observatory, plus each
    chunk-correlated finalize span attributed to its chunk's program
    as materialize (d2h) time."""
    from gelly_streaming_tpu.utils import costmodel

    cost_by_key = {}
    for row in cost_rows or []:
        cost_by_key[(row.get("program"), row.get("sig"))] = row
    measured = {}
    chunk_prog = {}
    # one time-ordered pass: chunk indices restart at 0 for every
    # pipelined call in the process, so a finalize must be attributed
    # to whichever program held its chunk id AT THAT TIME, not to the
    # last program that ever used the id
    for rec in sorted(leaf_spans(records),
                      key=lambda r: float(r.get("ts", 0.0))):
        a = rec.get("a") or {}
        prog = a.get("program")
        if prog:
            key = (prog, a.get("sig", "?"))
            m = measured.setdefault(key, {"count": 0, "total_s": 0.0,
                                          "materialize_s": 0.0})
            m["count"] += 1
            m["total_s"] += float(rec.get("dur", 0.0))
            if a.get("chunk") is not None:
                chunk_prog[(rec.get("trace"), a["chunk"])] = key
        elif rec.get("name") == "ingress.finalize":
            key = chunk_prog.get((rec.get("trace"), a.get("chunk")))
            if key is not None:
                measured[key]["materialize_s"] += float(
                    rec.get("dur", 0.0))
    rows = []
    for key, m in measured.items():
        entry = dict(cost_by_key.get(
            key, costmodel.classify({"program": key[0],
                                     "sig": key[1]})))
        costmodel.join_measure(entry, m["count"], m["total_s"])
        entry["materialize_s"] = round(m["materialize_s"], 6)
        rows.append(entry)
    rows.sort(key=lambda r: -(r.get("measured_total_s", 0.0)
                              + r.get("materialize_s", 0.0)))
    return rows


def tenant_attribution(records):
    """Per-tenant measured seconds over spans that carry the tenant
    label (the cohort's `tenant.single` dispatches, a tenant-labeled
    driver's steps), plus one `<cohort>` row aggregating the vmapped
    `cohort.dispatch` spans — whose time is SHARED by all tenants in
    the slab, so it is reported with its mean tenants-per-dispatch
    instead of being split by guesswork. Unlike the stage taxonomy,
    this table reads ALL spans (not just leaves): a tenant-labeled
    span legitimately envelopes its engine's internal chunk spans —
    its duration IS the tenant's wall time, and the table is rendered
    beside (never summed into) the conservation-checked stage totals.
    Empty ledger → empty list (the section only renders when a
    multi-tenant run produced it)."""
    per = {}
    cohort = {"count": 0, "total_s": 0.0, "tenants": 0, "edges": 0}
    for rec in (r for r in records if r.get("t") == "span"):
        a = rec.get("a") or {}
        if a.get("tenant") is not None:
            t = per.setdefault(str(a["tenant"]),
                               {"count": 0, "total_s": 0.0,
                                "edges": 0})
            t["count"] += 1
            t["total_s"] += float(rec.get("dur", 0.0))
            t["edges"] += int(a.get("edges") or 0)
        elif rec.get("name") == "cohort.dispatch":
            cohort["count"] += 1
            cohort["total_s"] += float(rec.get("dur", 0.0))
            cohort["tenants"] += int(a.get("tenants") or 0)
            cohort["edges"] += int(a.get("edges") or 0)
    rows = [dict(tenant=tid, count=t["count"],
                 total_s=round(t["total_s"], 6), edges=t["edges"])
            for tid, t in sorted(per.items())]
    if cohort["count"]:
        rows.append({
            "tenant": "<cohort>", "count": cohort["count"],
            "total_s": round(cohort["total_s"], 6),
            "edges": cohort["edges"],
            "mean_tenants_per_dispatch": round(
                cohort["tenants"] / cohort["count"], 2)})
    rows.sort(key=lambda r: -r["total_s"])
    return rows


def rank_suspects(stage_rows, prog_rows, records):
    """Deterministic heuristics → ranked suspect list, each with a
    score in [0, 1] and the evidence line an operator acts on."""
    stages = {r["stage"]: r for r in stage_rows}
    total = sum(r["total_s"] for r in stage_rows) or 1.0
    suspects = []

    storms = [r for r in records if r.get("t") == "event"
              and r.get("name") == "recompile_storm"]
    if storms:
        fns = sorted({(r.get("a") or {}).get("fn", "?")
                      for r in storms})
        suspects.append({
            "suspect": "recompile_storm", "score": 1.0,
            "evidence": "%d recompile_storm event(s) in the ledger "
                        "(fn: %s) — shape churn is recompiling per "
                        "dispatch; check bucket growth / signature "
                        "churn" % (len(storms), ", ".join(fns))})

    fin = stages.get("d2h+finalize", {"total_s": 0.0})["total_s"]
    if fin / total > 0.35:
        suspects.append({
            "suspect": "host_sync", "score": round(fin / total, 3),
            "evidence": "d2h+finalize holds %.0f%% of attributed time "
                        "— the materialize boundary (device→host "
                        "round trip) dominates; delta egress / deeper "
                        "chunks are the levers" % (100 * fin / total)})

    prep = stages.get("prep", {"total_s": 0.0})["total_s"]
    if prep / total > 0.40:
        suspects.append({
            "suspect": "prep_bound", "score": round(prep / total, 3),
            "evidence": "host prep holds %.0f%% of attributed time — "
                        "widen GS_PIPELINE_WORKERS or move to the "
                        "compact wire" % (100 * prep / total)})

    for row in prog_rows:
        roof = row.get("roofline_s")
        mean = row.get("measured_mean_s")
        if not roof or not mean:
            continue
        ratio = mean / roof
        if ratio > 20 and roof < 1e-3:
            import math

            suspects.append({
                "suspect": "launch_bound",
                "score": round(min(1.0, math.log10(ratio) / 3), 3),
                "evidence": "%s@%s: measured %.3g s/dispatch vs "
                            "roofline %.3g s (×%.0f) with a sub-ms "
                            "roofline — fixed dispatch overhead, not "
                            "compute, bounds it; batch more windows "
                            "per dispatch (resident tier)"
                            % (row.get("program"), row.get("sig"),
                               mean, roof, ratio)})
        elif row.get("bound") == "bytes" \
                and row.get("roofline_frac", 0) > 0.3:
            suspects.append({
                "suspect": "bytes_bound",
                "score": round(row["roofline_frac"], 3),
                "evidence": "%s@%s: bytes-bound at %.0f%% of its "
                            "roofline — intensity %.2f FLOPs/byte "
                            "under the machine balance; shrink the "
                            "wire (compact ingress / delta egress)"
                            % (row.get("program"), row.get("sig"),
                               100 * row["roofline_frac"],
                               row.get("arith_intensity_flops_per_byte")
                               or 0.0)})
    suspects.sort(key=lambda s: -s["score"])
    return suspects


def resolve_ledger(args, perf):
    """The ledger path: --ledger wins; else the committed cost_model
    section names one (repo-relative)."""
    if args.ledger:
        return args.ledger
    cm = (perf or {}).get("cost_model") or {}
    rel = cm.get("ledger")
    if rel:
        path = rel if os.path.isabs(rel) else os.path.join(REPO, rel)
        if os.path.exists(path):
            return path
    return None


def render(report, top=0):
    lines = ["explain_perf: trace=%s  (%d ledger records, %d leaf "
             "spans)" % (report["trace"] or "?",
                         report["ledger_records"],
                         report["leaf_spans"]), ""]
    lines += ["stage attribution (%.3f s attributed; ledger leaf "
              "total %.3f s; reconciled: %.1f%% mapped, tolerance "
              "%.1f%%):"
              % (report["attributed_total_s"],
                 report["ledger_total_s"],
                 100 * report["mapped_frac"],
                 100 * report["tolerance"])]
    lines += ["  %-14s %6s %10s %7s" % ("stage", "spans", "total s",
                                        "frac")]
    for r in report["stages"]:
        lines.append("  %-14s %6d %10.4f %6.1f%%"
                     % (r["stage"], r["count"], r["total_s"],
                        100 * r["frac"]))
    lines.append("")
    progs = report["programs"][:top] if top else report["programs"]
    if progs:
        lines.append("program attribution (dispatch spans tagged by "
                     "the cost observatory):")
        for r in progs:
            lines.append(
                "  %s@%s" % (r.get("program"), (r.get("sig") or "")[:48]))
            lines.append(
                "    dispatches=%s  dispatch_s=%s  materialize_s=%s  "
                "bound=%s" % (r.get("dispatches"),
                              r.get("measured_total_s"),
                              r.get("materialize_s"),
                              r.get("bound")))
            if r.get("flops"):
                lines.append(
                    "    flops=%s bytes=%s intensity=%s "
                    "roofline_frac=%s achieved=%s GFLOP/s"
                    % (r.get("flops"), r.get("bytes_accessed"),
                       r.get("arith_intensity_flops_per_byte"),
                       r.get("roofline_frac"),
                       r.get("achieved_gflops")))
        lines.append("")
    if report.get("tenants"):
        lines.append("tenant attribution (tenant-labeled spans; "
                     "<cohort> rows are shared vmapped dispatches):")
        for r in report["tenants"]:
            extra = ("  tenants/dispatch=%s"
                     % r["mean_tenants_per_dispatch"]
                     if "mean_tenants_per_dispatch" in r else "")
            lines.append("  %-16s spans=%-5d total_s=%-10.4f "
                         "edges=%d%s" % (r["tenant"], r["count"],
                                         r["total_s"], r["edges"],
                                         extra))
        lines.append("")
    if report["suspects"]:
        lines.append("ranked suspects:")
        for i, s in enumerate(report["suspects"], 1):
            lines.append("  %d. [%.2f] %s — %s"
                         % (i, s["score"], s["suspect"],
                            s["evidence"]))
    else:
        lines.append("no suspects fired — the run tracks its "
                     "roofline within the heuristics' thresholds")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--ledger", default=None,
                    help="run ledger (trace_*.jsonl); default: the "
                         "one the --perf cost_model section names")
    ap.add_argument("--perf", default=None,
                    help="PERF*.json with a cost_model section "
                         "(FLOPs/bytes per program)")
    ap.add_argument("--trace-id", default=None,
                    help="narrow the ledger to one run's records")
    ap.add_argument("--regression", default=None,
                    help="bench_compare --out report: print the "
                         "regressions + trace correlation first")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="stage-total conservation tolerance "
                         "(default 0.05 = 5%%)")
    ap.add_argument("--top", type=int, default=0,
                    help="limit the program table to the top N rows")
    ap.add_argument("--json", action="store_true",
                    help="print the report as JSON instead of text")
    args = ap.parse_args(argv)

    perf = None
    if args.perf:
        try:
            with open(args.perf) as f:
                perf = json.load(f)
        except (OSError, ValueError) as e:
            print("explain_perf: unreadable --perf %s (%s)"
                  % (args.perf, e), file=sys.stderr)
            return 2

    regression = None
    if args.regression:
        try:
            with open(args.regression) as f:
                regression = json.load(f)
        except (OSError, ValueError) as e:
            print("explain_perf: unreadable --regression %s (%s)"
                  % (args.regression, e), file=sys.stderr)
            return 2
        for r in regression.get("regressions") or []:
            print("regression: %s.%s %s -> %s (x%s)%s"
                  % (r.get("row"), r.get("field"), r.get("baseline"),
                     r.get("current"), r.get("ratio"),
                     "  [trace %s -> %s]" % (r.get("baseline_trace"),
                                             r.get("current_trace"))
                     if r.get("current_trace") else ""),
                  file=sys.stderr)
        if args.trace_id is None \
                and regression.get("current_trace"):
            args.trace_id = regression["current_trace"]

    ledger = resolve_ledger(args, perf)
    if ledger is None:
        print("explain_perf: no ledger — pass --ledger, or --perf "
              "with a cost_model section that names one",
              file=sys.stderr)
        return 2
    records = trace_report.load(ledger)
    records = trace_report.filter_records(records, args.trace_id)
    if not [r for r in records if r.get("t") == "span"]:
        print("explain_perf: no span records in %s%s — arm "
              "GS_TELEMETRY=1 (and GS_COSTMODEL=1 for program tags) "
              "and flush" % (ledger,
                             " matching --trace-id %s" % args.trace_id
                             if args.trace_id else ""),
              file=sys.stderr)
        return 1

    cost_rows = ((perf or {}).get("cost_model") or {}).get("programs")
    stages, attributed, ledger_total, unmapped = \
        stage_attribution(records)
    other_s = sum(r["total_s"] for r in stages
                  if r["stage"] == "other")
    mapped_frac = (1.0 - other_s / ledger_total if ledger_total > 0
                   else 1.0)
    programs = program_attribution(records, cost_rows)
    tenants = tenant_attribution(records)
    suspects = rank_suspects(stages, programs, records)
    report = {
        "trace": trace_report.meta_of(records).get("trace"),
        "ledger": ledger,
        "ledger_records": len(records),
        "leaf_spans": len(leaf_spans(records)),
        "tolerance": args.tolerance,
        "attributed_total_s": attributed,
        "ledger_total_s": ledger_total,
        "mapped_frac": round(mapped_frac, 4),
        "unmapped_spans": unmapped,
        "stages": stages,
        "programs": programs,
        "tenants": tenants,
        "suspects": suspects,
    }
    if regression is not None:
        report["regression"] = {
            "path": args.regression,
            "rows": regression.get("regressions"),
            "baseline_trace": regression.get("baseline_trace"),
            "current_trace": regression.get("current_trace"),
        }
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(render(report, args.top))
    if mapped_frac < 1.0 - args.tolerance:
        print("explain_perf: the stage taxonomy could not name "
              "%.1f%% of the ledger's leaf-span time (> %.1f%% "
              "tolerance) — unmapped spans: %s; add them to STAGE_OF "
              "(or CONTAINERS if they envelope other spans)"
              % (100 * (1.0 - mapped_frac), 100 * args.tolerance,
                 ", ".join(unmapped) or "?"), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Ingress A/B: is the streaming window counter's end-to-end rate
bound by h2d transfer, per-dispatch latency, or device compute — and
does a compact ingress format fix it?

The standard stream dispatch (TriangleWindowKernel._run_stack) ships
9 bytes per edge-slot h2d: src int32 + dst int32 + valid bool. But
(a) vertex ids fit uint16 whenever vertex_bucket <= 65536 (every
bench scale), and (b) padding is always a per-window SUFFIX
(seg_ops.window_stack), so the [wb, eb] bool mask is reconstructible
from one int32 count per window. Compact ingress sends
uint16 src + uint16 dst + int32 nvalid[wb] = 4 bytes/slot (2.25x
fewer bytes), widening + mask reconstruction fused into the same
window program on device (VPU-cheap).

Four probes, each a JSON line:
  h2d_probe            — device_put bandwidth at both formats (bytes/s)
  latency_probe        — round-trip of a minimal 1-window dispatch (s)
  device_compute_probe — one stream-chunk program on already-resident
                         data (pure device compute; completes the
                         transfer + dispatch + compute decomposition)
  stream_ab            — full-stream end-to-end, standard vs compact,
                         identical counts asserted window-by-window

Run AFTER the evidence queue (tools/tpu_queue.sh) — it shares the
tunnel and the single host core. Results go to stdout and
logs/ingress_ab_<backend>.json; the kernel only ADOPTS compact
ingress behind the same committed-evidence policy as every other
selection (ops/triangles.py docstrings).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from bench import make_stream  # noqa: E402  (the A/B stream IS the bench stream)


def _median_time(fn, reps=5, warmup=1):
    return _timed_stats(fn, reps, warmup)[0]


def _timed_stats(fn, reps=5, warmup=1):
    """(median, min, max) wall seconds — the stream A/B commits the
    whole trio so the 1.05x adoption bar is never decided by one
    load-noisy draw (the 1.13x/1.02x flip-flop across consecutive
    committed runs, PERF.md)."""
    for _ in range(warmup):
        fn()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts)), float(np.min(ts)), float(np.max(ts))


def h2d_probe(jax, jnp, eb, wb, results):
    """device_put bandwidth of one stream chunk in each format."""
    slots = wb * eb
    rng = np.random.default_rng(0)
    s32 = rng.integers(0, 65536, (wb, eb)).astype(np.int32)
    d32 = rng.integers(0, 65536, (wb, eb)).astype(np.int32)
    v8 = np.ones((wb, eb), bool)
    s16 = s32.astype(np.uint16)
    d16 = d32.astype(np.uint16)
    nv = np.full(wb, eb, np.int32)

    def put(*arrs):
        out = [jax.device_put(a) for a in arrs]
        jax.block_until_ready(out)

    t_std = _median_time(lambda: put(s32, d32, v8))
    t_cmp = _median_time(lambda: put(s16, d16, nv))
    row = {
        "probe": "h2d",
        "backend": jax.default_backend(),
        "chunk_slots": slots,
        "std_bytes": slots * 9,
        "std_s": round(t_std, 6),
        "std_bytes_per_s": round(slots * 9 / t_std),
        "compact_bytes": slots * 4 + 4 * wb,
        "compact_s": round(t_cmp, 6),
        "compact_bytes_per_s": round((slots * 4 + 4 * wb) / t_cmp),
        "speedup": round(t_std / t_cmp, 2),
    }
    results.append(row)
    print(json.dumps(row), flush=True)


def latency_probe(jax, jnp, results):
    """Fixed round-trip cost of a minimal dispatch (scalar in/out)."""
    one = jnp.ones((8,), jnp.int32)

    @jax.jit
    def tick(x):
        return x.sum()

    t = _median_time(lambda: jax.block_until_ready(tick(one)), reps=9)
    row = {"probe": "dispatch_latency",
           "backend": jax.default_backend(), "round_trip_s": round(t, 6)}
    results.append(row)
    print(json.dumps(row), flush=True)


def device_compute_probe(jax, jnp, results):
    """Pure device time of ONE stream-chunk program on ALREADY-resident
    data: with the h2d probe (transfer) and the latency probe
    (dispatch round-trip), this completes the end-to-end rate's
    decomposition — rate ≈ chunk_edges / (transfer + dispatch +
    compute) — so the residual after compact ingress + deep chunks is
    attributable, not mysterious (VERDICT r4 item 1's 'fully
    decomposed' done-criterion)."""
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    eb, vb = 32768, 65536
    k = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb,
                             ingress="standard")
    wb = k.MAX_STREAM_WINDOWS
    rng = np.random.default_rng(5)
    s = jax.device_put(
        rng.integers(0, vb, (wb, eb)).astype(np.int32))
    d = jax.device_put(
        rng.integers(0, vb, (wb, eb)).astype(np.int32))
    valid = jax.device_put(np.ones((wb, eb), bool))
    ex = k._stream_exec(wb)   # AOT-compiled executable
    t = _median_time(
        lambda: jax.block_until_ready(ex(s, d, valid)), reps=5)
    row = {
        "probe": "device_compute",
        "backend": jax.default_backend(),
        "eb": eb, "k": k.kb, "windows_per_dispatch": wb,
        "chunk_edges": wb * eb,
        "compute_s": round(t, 4),
        "per_window_ms": round(t / wb * 1e3, 3),
        "compute_only_edges_per_s": round(wb * eb / t),
    }
    results.append(row)
    print(json.dumps(row), flush=True)


def stream_ab(jax, jnp, num_edges, results):
    """Both ingress formats through the kernel's OWN adopted dispatch
    path (TriangleWindowKernel(ingress=...)._count_stream_device), so
    the measured forms are exactly the shipping ones."""
    from gelly_streaming_tpu.ops.triangles import TriangleWindowKernel

    eb, vb = 32768, 65536
    src, dst = make_stream(num_edges, vb)
    k_std = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb,
                                 ingress="standard")
    k_cmp = TriangleWindowKernel(edge_bucket=eb, vertex_bucket=vb,
                                 ingress="compact")
    k_std.warm_chunks()
    k_cmp.warm_chunks()

    counts_std = counts_cmp = None

    def run_std():
        nonlocal counts_std
        counts_std = k_std._count_stream_device(src, dst)

    def run_cmp():
        nonlocal counts_cmp
        counts_cmp = k_cmp._count_stream_device(src, dst)

    t_std, t_std_min, t_std_max = _timed_stats(run_std, reps=3,
                                               warmup=1)
    t_cmp, t_cmp_min, t_cmp_max = _timed_stats(run_cmp, reps=3,
                                               warmup=1)
    # A parity failure is committed as evidence ({parity: false}, no
    # speedup claim) instead of crashing the tool and losing the whole
    # section's probe rows; the selection gate (rows_clear_bar)
    # rejects the row, so compact ingress is never adopted on it.
    parity = counts_std == counts_cmp
    row = {
        "probe": "stream_ab",
        "backend": jax.default_backend(),
        "num_edges": len(src),
        "eb": eb, "k": k_std.kb,
        "windows_per_dispatch": k_std.MAX_STREAM_WINDOWS,
        "std_s": round(t_std, 3),
        "std_s_min": round(t_std_min, 3),
        "std_s_max": round(t_std_max, 3),
        "std_edges_per_s": round(len(src) / t_std),
        "compact_s": round(t_cmp, 3),
        "compact_s_min": round(t_cmp_min, 3),
        "compact_s_max": round(t_cmp_max, 3),
        "compact_edges_per_s": round(len(src) / t_cmp),
        "parity": bool(parity),
    }
    if parity:
        row["speedup"] = round(t_std / t_cmp, 3)
        # the dispersion envelope's pessimistic/optimistic pairings:
        # adopt only when even speedup_worst argues the win is real,
        # not a single lucky draw
        row["speedup_worst"] = round(t_std_min / t_cmp_max, 3)
        row["speedup_best"] = round(t_std_max / t_cmp_min, 3)
    else:
        print("PARITY FAILURE between ingress forms", file=sys.stderr)
    results.append(row)
    print(json.dumps(row), flush=True)


PROBE_NAMES = ("latency", "h2d", "device_compute", "stream_ab")


def commit_results(results, backend: str) -> None:
    """Merge this run's rows into the committed evidence under the
    same policy as tools/profile_kernels.py's flush: `ingress_ab`
    carries ONLY the stream_ab rows (resolve_ingress's gate checks
    parity+speedup on every row), the other probes land under
    `ingress_probes`; PERF.json updates only when its backend label
    matches the LIVE backend (a CPU run never overwrites chip-labeled
    selections), while the per-backend archive PERF_<backend>.json
    always takes the rows (ops/triangles._load_matching_perf reads it
    when PERF.json belongs to the other backend). Only keys this run
    produced are replaced — a stream_ab-only run keeps the committed
    bandwidth/latency probes."""
    ab = [r for r in results if r.get("probe") == "stream_ab"]
    probes = [r for r in results if r.get("probe") != "stream_ab"]
    targets = ((os.path.join(REPO, "PERF.json"), True),
               (os.path.join(REPO, "PERF_%s.json" % backend), False))
    for path, need_match in targets:
        try:
            with open(path) as f:
                cur = json.load(f)
        except (OSError, ValueError):
            cur = {}
        if need_match and cur.get("backend") != backend:
            print("not committing to %s: file backend %r != live %r"
                  % (os.path.basename(path), cur.get("backend"),
                     backend), file=sys.stderr)
            continue
        cur.setdefault("backend", backend)
        if ab:
            cur["ingress_ab"] = ab
        if probes:
            cur["ingress_probes"] = probes
        with open(path, "w") as f:
            json.dump(cur, f, indent=2)  # the profiler's format
        print("committed %s row(s) to %s"
              % (len(ab) + len(probes), os.path.basename(path)),
              flush=True)


def main():
    import argparse

    ap = argparse.ArgumentParser()
    # validated by hand, not via `choices`: argparse on Python <= 3.11
    # rejects an EMPTY nargs='*' list against choices, which would
    # break the documented no-argument run-everything invocation
    ap.add_argument("probes", nargs="*",
                    help="subset of %s to run (default: all)"
                         % (PROBE_NAMES,))
    ap.add_argument("--edges", type=int,
                    default=int(os.environ.get("GS_AB_EDGES", 10_485_760)))
    ap.add_argument("--commit", action="store_true",
                    help="merge rows into PERF.json (backend-matched) "
                         "and PERF_<backend>.json")
    args = ap.parse_args()
    bad = [p for p in args.probes if p not in PROBE_NAMES]
    if bad:
        ap.error("unknown probe(s) %s; valid: %s"
                 % (bad, list(PROBE_NAMES)))
    want = args.probes or list(PROBE_NAMES)

    import jax
    import jax.numpy as jnp

    results = []
    if "latency" in want:
        latency_probe(jax, jnp, results)
    if "h2d" in want:
        h2d_probe(jax, jnp, 32768, 16, results)
    if "device_compute" in want:
        device_compute_probe(jax, jnp, results)
    if "stream_ab" in want:
        stream_ab(jax, jnp, args.edges, results)
    out = os.path.join(REPO, "logs",
                       "ingress_ab_%s.json" % jax.default_backend())
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote %s" % out, flush=True)
    if args.commit:
        commit_results(results, jax.default_backend())


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""CI gate for the tenant observatory (tools/ci_check.sh [13/13]):

an 8-tenant cohort runs armed (GS_PROVENANCE=1 + WAL + auto
checkpoints), then tools/replay_window.py re-derives EVERY provenance
record the run emitted — nearest checkpoint, WAL replay strictly
across the recorded span, recompute, digest diff — on TWO tiers: the
host twin (no compiler, no device) and the fused scan engine. The
gate fails when

  - any record's recomputed digest mismatches the ledger's,
  - any record is skipped for ANY reason (a silently-unverifiable
    ledger is worse than none: it claims an audit trail it cannot
    back),
  - any delivered window is MISSING from the ledger (emission
    coverage: every finalize owner must write its record),
  - the two replay tiers disagree with each other.

Deterministic end to end: seeded streams, no faults, no timing
dependence.
"""

import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from tools.tenancy_ab import scoped_env  # noqa: E402

EB, VB = 512, 1024
TENANTS = 8
WINDOWS_PER_TENANT = 3


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="gs_prov_smoke_")
    wal_dir = os.path.join(tmp, "wal")
    prov_dir = os.path.join(tmp, "prov")
    ckpt_dir = os.path.join(tmp, "ckpt")
    with scoped_env(GS_PROVENANCE="1", GS_PROVENANCE_DIR=prov_dir,
                    GS_WAL="1"):
        from gelly_streaming_tpu.core.tenancy import TenantCohort
        from gelly_streaming_tpu.utils import provenance
        from tools import replay_window

        cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
        assert cohort.enable_wal(wal_dir)
        cohort.enable_auto_checkpoint(ckpt_dir, every_n_windows=2)
        rng = np.random.default_rng(7)
        delivered = {}
        for i in range(TENANTS):
            cohort.admit("tenant-%d" % i)
        for i in range(TENANTS):
            n = WINDOWS_PER_TENANT * EB
            cohort.feed("tenant-%d" % i,
                        rng.integers(0, VB, n).astype(np.int64),
                        rng.integers(0, VB, n).astype(np.int64))
        for tid, rows in cohort.pump().items():
            delivered.setdefault(tid, []).extend(rows)
        # a ragged close: the short final window's record must carry
        # its EXACT covered span (not the nominal eb)
        cohort.feed("tenant-0",
                    rng.integers(0, VB, EB // 2).astype(np.int64),
                    rng.integers(0, VB, EB // 2).astype(np.int64))
        delivered.setdefault("tenant-0", []).extend(
            cohort.close("tenant-0"))

        n_delivered = sum(len(v) for v in delivered.values())
        recs, torn = replay_window.load_records(prov_dir)
        if torn is not None:
            print("FAIL: torn provenance tail in a clean run: %s"
                  % torn)
            return 1
        cohort_recs = [r for r in recs
                       if r["tier"] in ("cohort", "cohort_resident")]
        if len(cohort_recs) != n_delivered:
            print("FAIL: delivered %d windows but the ledger holds %d "
                  "cohort-tier records — a finalize owner skipped its "
                  "emission" % (n_delivered, len(cohort_recs)))
            return 1

        ok = True
        for tier in ("host", "scan"):
            rep = replay_window.replay_all(
                prov_dir, wal_dir, ckpt=ckpt_dir, tier=tier,
                eb=EB, vb=VB)
            print("[provenance_smoke] tier=%-4s records=%d "
                  "verified=%d mismatched=%d skipped=%d"
                  % (tier, rep["records"], rep["verified"],
                     rep["mismatched"], rep["skipped"]))
            if rep["records"] == 0:
                print("FAIL: armed run emitted no provenance records")
                ok = False
            if rep["mismatched"] or rep["skipped"]:
                for r in rep["rows"]:
                    if not r["ok"]:
                        print("  %s w%d [%s]: %s"
                              % (r["tenant"], r["window"], r["tier"],
                                 r["skipped"] or "digest mismatch "
                                 "(%s != %s)" % (r["computed"],
                                                 r["recorded"])))
                ok = False
        if not ok:
            return 1
        print("[provenance_smoke] PASS: %d records verified on 2 "
              "tiers (%d windows delivered, knobs %s)"
              % (len(recs), n_delivered, provenance.knob_fingerprint()))
        return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Latency-plane smoke — CI gate 8 (tools/ci_check.sh).

One tenant fed through a REAL loopback socket into an armed
(GS_LATENCY=1 + GS_TELEMETRY=1 + GS_METRICS=1) journal-backed
`core/serve.StreamServer`, pumped and drained. Checks, in order:

  1. every delivered results row carries the self-throttle fields
     (`latency_s`, `queue_edges`) and the `status` op serves the
     per-tenant queue depth+age and the `latency` section;
  2. the run's `/healthz` body has a POPULATED `latency` section
     (per-tenant e2e percentiles, oldest-unfinalized-edge age key,
     SLO state when a target is set);
  3. the flushed run ledger reconciles: tools/latency_report.py over
     the real serve run must find every window's stage decomposition
     summing to its measured ingest→deliver end-to-end within 5%
     (non-zero exit otherwise) — the acceptance bar of the latency
     plane, held on every CI run;
  4. serve results are digest-identical to the same stream fed with
     the plane DISARMED (the observation-only contract).

Exit 0 = clean. Runs in seconds on the CPU backend.
"""

import hashlib
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

_KNOBS = ("GS_LATENCY", "GS_TELEMETRY", "GS_TRACE_DIR", "GS_METRICS",
          "GS_SLO_P99_S")


def digest_summaries(summaries) -> str:
    h = hashlib.sha256()
    for s in summaries:
        h.update(json.dumps(s, sort_keys=True).encode())
    return h.hexdigest()[:16]


def serve_run(eb, vb, num_w, src, dst, wd=None):
    """Feed → pump → drain one loopback server; returns (summary rows,
    full sink rows)."""
    from gelly_streaming_tpu.core.serve import (ServeClient,
                                                StreamServer)
    from gelly_streaming_tpu.core.tenancy import TenantCohort

    cohort = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    if wd is not None:
        cohort.enable_wal(os.path.join(wd, "wal"))
    server = StreamServer(cohort, port=0).start()
    cli = ServeClient(server.port)
    status = None
    try:
        assert cli.admit("t1")["ok"]
        for i in range(num_w):
            r = cli.feed("t1", src[i * eb:(i + 1) * eb],
                         dst[i * eb:(i + 1) * eb])
            assert r.get("ok"), r
            cli.pump()
        status = cli.status()["serve"]
    finally:
        cli.close()
    server.drain(deadline_s=5)
    rows = list(server.results.get("t1", []))
    server.close()
    return rows, status


def main() -> int:
    eb, vb, num_w = 512, 1024, 5
    from bench import make_stream

    src, dst = make_stream(num_w * eb, vb, seed=11)
    src, dst = src.astype(np.int32), dst.astype(np.int32)

    prev = {k: os.environ.get(k) for k in _KNOBS}
    from gelly_streaming_tpu.utils import latency, metrics, telemetry
    try:
        # disarmed oracle first (fresh planes)
        for k in _KNOBS:
            os.environ[k] = "0" if k != "GS_TRACE_DIR" else ""
        latency.reset(), metrics.reset(), telemetry.reset()
        base_rows, _ = serve_run(eb, vb, num_w, src, dst)
        if any("latency_s" in row for row in base_rows):
            print("latency smoke FAILED: disarmed rows carry "
                  "latency fields")
            return 1
        want = digest_summaries([r["summary"] for r in base_rows])

        with tempfile.TemporaryDirectory(prefix="gs-lat-smoke-") as wd:
            os.environ["GS_LATENCY"] = "1"
            os.environ["GS_TELEMETRY"] = "1"
            os.environ["GS_METRICS"] = "1"
            os.environ["GS_TRACE_DIR"] = wd
            os.environ["GS_SLO_P99_S"] = "30"  # populated, not burning
            latency.reset(), metrics.reset(), telemetry.reset()
            rows, status = serve_run(eb, vb, num_w, src, dst, wd=wd)

            # 1. self-throttle fields on every delivered row + status
            missing = [r["window"] for r in rows
                       if "latency_s" not in r
                       or "queue_edges" not in r]
            if missing:
                print("latency smoke FAILED: rows without latency/"
                      "queue fields: %s" % missing)
                return 1
            if "queues" not in status or "latency" not in status:
                print("latency smoke FAILED: status lacks queues/"
                      "latency sections: %s" % sorted(status))
                return 1

            # 2. /healthz latency section populated
            snap = metrics.health_snapshot()
            lat = snap.get("latency") or {}
            if not lat.get("enabled") \
                    or "t1" not in lat.get("tenants", {}) \
                    or "oldest_unfinalized_age_s" not in lat \
                    or not lat.get("slo"):
                print("latency smoke FAILED: /healthz latency section "
                      "not populated: %s" % json.dumps(lat))
                return 1
            t1 = lat["tenants"]["t1"]
            if t1["windows"] != num_w or t1["e2e_p99_s"] <= 0:
                print("latency smoke FAILED: t1 percentile row is "
                      "empty: %s" % t1)
                return 1

            # 3. ledger waterfalls reconcile within 5%
            telemetry.flush()
            ledger = telemetry.ledger_path()
            if ledger is None:
                print("latency smoke FAILED: no run ledger was "
                      "written")
                return 1
            from tools import latency_report

            rc = latency_report.main([ledger, "--tolerance", "0.05"])
            if rc != 0:
                print("latency smoke FAILED: waterfall "
                      "reconciliation rc=%d" % rc)
                return 1
            telemetry.reset()  # close the ledger inside the tempdir

        # 4. armed ≡ disarmed summaries
        got = digest_summaries([r["summary"] for r in rows])
        if got != want or len(rows) != len(base_rows):
            print("latency smoke FAILED: armed digest %s (%d) != "
                  "disarmed %s (%d)"
                  % (got, len(rows), want, len(base_rows)))
            return 1
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        latency.reset(), metrics.reset(), telemetry.reset()
    print("latency smoke ok: %d windows delivered with latency_s, "
          "/healthz latency populated, waterfalls reconcile, armed "
          "≡ disarmed (%s)" % (len(rows), want))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Chaos soak: replay a FIXED fault schedule against the streaming
runtime and assert the final window-by-window counts are identical to
the fault-free run — the end-to-end proof that the resilience layer
(stage watchdogs + bounded retry, window-boundary checkpoint/resume,
error-path drain) changes AVAILABILITY, never results.

Schedule (all deterministic, utils/faults — no randomness anywhere):

  leg A — StreamingAnalyticsDriver over the 524K/32768 CPU row
          (bench.make_stream), streamed from a file in ~1 MB pieces
          with auto-checkpoints every 4 windows:
            · 1 transient prep failure   (retried, GS_STAGE_RETRIES)
            · 1 h2d stall                (cut by GS_STAGE_TIMEOUT_S,
                                          retried; fires only where
                                          the selected triangle tier
                                          routes run_pipeline — leg B
                                          guarantees the class)
            · 1 fatal mid-stream kill    (fatal InjectedFault) →
              try_resume + resume re-feed, at-least-once dedup by
              window_start
  leg B — StreamSummaryEngine (fused scan; run_pipeline h2d is always
          live here) fed in 4-window calls:
            · 1 h2d stall → timeout → retry
            · 1 transient prep failure → retry
            · 1 fatal kill mid-call → fresh engine resumes from its
              auto-checkpoint, positional combine

  leg G — the GNN drill (ops/gnn_window): a journal-armed
          GnnSummaryEngine killed fatally mid-stream → newest
          checkpoint + WAL-suffix replay → summary stream AND the
          final [vb, F] feature slab bit-identical to the fault-free
          oracle (the dyadic-lattice exactness contract survives the
          crash)

  leg R — the RESIDENT drill: the driver pinned to the resident
          megakernel (ops/resident_engine), fatal kill MID-SUPERBATCH
          → auto-checkpoint resume → window-by-window sha256 parity
          with the fault-free SCAN-tier oracle (cross-tier: the
          donated carry never leaks a half-applied super-batch)

  leg S — the SERVE drill (core/serve.py + utils/wal.py): two
          tenants fed through a real loopback socket into a
          journal-armed StreamServer
            · fatal kill mid-window  → fresh cohort recovers
              (checkpoint resume + WAL suffix replay) and the
              per-tenant digests equal the fault-free direct oracle
            · torn journal tail      → recovery falls back exactly
              one record (durable wal_torn_tail), resend restores
              parity
            · slow client            → a stalled response send is
              shed (durable serve_client_shed); the pump keeps
              serving
            · SIGTERM drain          → a standalone subprocess exits
              0 with every accepted window in its results file and a
              SEALED journal

  leg P — the POISON drill (utils/sanitize + the core/tenancy
          bulkhead, GS_SANITIZE=on): an 8-tenant cohort with one
          hostile tenant flooding garbage (byte soup through
          native.parse_edge_bytes + a dispatch poison riding its
          batches) — the bulkhead bisects the failing dispatch to the
          hostile tenant and quarantines it, the 7 healthy tenants'
          digests stay bit-identical to the fault-free oracle, every
          rejected edge reconciles against the dead-letter journal,
          and a serve subprocess under the same flood SIGTERM-drains
          with exit 0

  leg M — the MESH drill (virtual n-device CPU mesh, armed via
          --mesh-devices; the process pins a CPU backend with that
          many virtual devices before jax initializes): a sharded
          driver streamed with
            · 1 corrupt shard wire        (GS_MESH_WIRE_CHECK=1
                                           catches it; retried clean)
            · 1 DEAD SHARD mid-stream     (persistent shard_dispatch
                                           failure) → the sharded →
              single-chip-scan demotion ladder re-enters from the
              last finalized chunk, and the final window-by-window
              digests still equal the fault-free single-chip oracle
          plus the cross-mesh-shape resume proof: a checkpoint taken
          on the n-way mesh resumes bit-exactly on 1 device (scan
          tier) AND on the numpy host tier.

The tool FAILS unless (a) every fault class actually fired somewhere,
and (b) every leg's outputs are bit-identical (sha256 over the full
snapshot arrays, not just scalars) to their fault-free twins.

Usage:
  python tools/chaos_run.py [--edges 524288] [--eb 32768]
                            [--vertices 65536] [--engine-windows N]
                            [--mesh-devices 4] [--out CHAOS.json]
"""

import argparse
import hashlib
import json
import os
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import make_stream  # noqa: E402
from gelly_streaming_tpu.core.driver import (  # noqa: E402
    StreamingAnalyticsDriver)
from gelly_streaming_tpu.ops.scan_analytics import (  # noqa: E402
    StreamSummaryEngine)
from gelly_streaming_tpu.utils import (  # noqa: E402
    faults, resilience, telemetry)

KNOBS = {"GS_STAGE_TIMEOUT_S": "1", "GS_STAGE_RETRIES": "2",
         "GS_STAGE_BACKOFF_S": "0.05"}


def _digest(r) -> tuple:
    h = hashlib.sha256()
    for a in (r.vertex_ids, r.degrees, r.cc_labels, r.bipartite_odd):
        if a is not None:
            h.update(np.ascontiguousarray(a).tobytes())
    return (int(r.window_start), int(r.num_edges),
            None if r.triangles is None else int(r.triangles),
            h.hexdigest()[:16])


def _write_stream(path: str, src, dst) -> None:
    with open(path, "w") as f:
        for s, d in zip(src.tolist(), dst.tolist()):
            f.write("%d %d\n" % (s, d))


def _driver(eb: int) -> StreamingAnalyticsDriver:
    return StreamingAnalyticsDriver(
        window_ms=0, edge_bucket=eb, vertex_bucket=1024,
        analytics=("degrees", "cc", "bipartite", "triangles"))


def leg_driver(path: str, eb: int, num_w: int, workdir: str) -> dict:
    piece = 1 << 20  # ~1 MB pieces → several run_arrays calls
    baseline = [
        _digest(r)
        for r in _driver(eb).stream_file(path, chunk_bytes=piece)]
    assert len(baseline) == num_w, (len(baseline), num_w)

    ckpt = os.path.join(workdir, "driver.npz")
    fired = []
    drv = _driver(eb)
    drv.enable_auto_checkpoint(ckpt, every_n_windows=4)
    got = {}
    plan_specs = [
        faults.FaultSpec(site="prep", on_call=1),            # retried
        faults.FaultSpec(site="h2d", on_call=1,              # stalled,
                         action="hang", seconds=2.5),        # retried
        faults.FaultSpec(site="dispatch", on_call=4,         # THE KILL
                         fatal=True),
    ]
    killed = False
    try:
        with faults.inject(*plan_specs) as plan:
            for r in drv.stream_file(path, chunk_bytes=piece):
                got[_digest(r)[0]] = _digest(r)
    except faults.InjectedFault:
        killed = True
        fired = list(plan.fired)
    if not killed:
        raise SystemExit("chaos leg A: the kill never fired "
                         "(fired=%r)" % (plan.fired,))

    drv2 = _driver(eb)
    if not drv2.try_resume(ckpt):
        # killed before the first checkpoint flushed: full re-feed
        drv2 = _driver(eb)
    resumed_from = drv2.windows_done
    for r in drv2.stream_file(path, chunk_bytes=piece,
                              resume=resumed_from > 0):
        got[_digest(r)[0]] = _digest(r)  # at-least-once: keep last

    final = [got[k] for k in sorted(got)]
    if final != baseline:
        raise SystemExit("chaos leg A DIVERGED from the fault-free run")
    return {
        "windows": num_w,
        "resumed_from_window": resumed_from,
        "faults_fired": [list(f) for f in fired],
        "parity": True,
    }


def leg_engine(src, dst, eb: int, vb: int, num_w: int,
               workdir: str) -> dict:
    src = np.asarray(src, np.int32)[:num_w * eb]
    dst = np.asarray(dst, np.int32)[:num_w * eb]
    if int(src.max()) >= vb or int(dst.max()) >= vb:
        raise SystemExit("leg B ids must fit its vertex bucket")
    # the fault-free oracle carries the leg's cold compiles: give
    # them the 30s guard the other legs take (a loaded box can push a
    # compile past 1s). The fault-armed run below reuses the jit
    # cache, so the 1s deadline it needs to CUT the injected 2.5s
    # hang still bites only the stall, never a compile. The armed
    # loop feeds call_w-window calls — a DIFFERENT window bucket than
    # the oracle's full-stream chunks — so that program is warmed
    # here too, on a throwaway engine, before any fault arms
    call_w = 4
    env_prev = os.environ.get("GS_STAGE_TIMEOUT_S")
    os.environ["GS_STAGE_TIMEOUT_S"] = "30"
    try:
        baseline = StreamSummaryEngine(edge_bucket=eb,
                                       vertex_bucket=vb).process(src,
                                                                 dst)
        StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb).process(
            src[:call_w * eb], dst[:call_w * eb])
    finally:
        if env_prev is None:
            os.environ.pop("GS_STAGE_TIMEOUT_S", None)
        else:
            os.environ["GS_STAGE_TIMEOUT_S"] = env_prev

    ckpt = os.path.join(workdir, "engine.npz")
    eng = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    eng.enable_auto_checkpoint(ckpt, every_n_windows=4)
    fired = []
    out = []
    plans = {
        0: [faults.FaultSpec(site="h2d", on_call=1, action="hang",
                             seconds=2.5),
            faults.FaultSpec(site="prep", on_call=2)],
        1: [faults.FaultSpec(site="dispatch", on_call=1, fatal=True)],
    }
    killed_at = None
    for call, lo in enumerate(range(0, num_w, call_w)):
        s = src[lo * eb:(lo + call_w) * eb]
        d = dst[lo * eb:(lo + call_w) * eb]
        try:
            with faults.inject(*plans.get(call, [])) as plan:
                out += eng.process(s, d)
            fired += list(plan.fired)
        except faults.InjectedFault:
            fired += list(plan.fired)
            killed_at = call
            break
    if killed_at is None:
        raise SystemExit("chaos leg B: the kill never fired")
    eng2 = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
    if not eng2.try_resume(ckpt):
        raise SystemExit("chaos leg B: no resumable checkpoint after "
                         "the kill")
    off = eng2.resume_offset()
    rest = eng2.process(src[off:], dst[off:])
    final = out[:off // eb] + rest  # positional at-least-once combine
    if final != baseline:
        raise SystemExit("chaos leg B DIVERGED from the fault-free run")
    return {
        "windows": num_w,
        "killed_at_call": killed_at,
        "resumed_from_window": off // eb,
        "faults_fired": [list(f) for f in fired],
        "parity": True,
    }


def leg_gnn(workdir: str) -> dict:
    """The windowed-GNN leg: a journal-armed GnnSummaryEngine killed
    fatally mid-stream → newest checkpoint + WAL-suffix replay → the
    summary stream AND the final [vb, F] feature slab bit-identical
    to the fault-free oracle. The dyadic-lattice exactness contract
    (ops/gnn_window) must survive a crash, not just a clean run: a
    replayed dense update that drifted by one lattice unit would
    flip the slab digest here."""
    from gelly_streaming_tpu.ops import gnn_window as gw

    eb, vb, F, num_w = 512, 2048, 16, 8
    src, dst = make_stream(num_w * eb, vb, seed=29)
    src = np.asarray(src, np.int32)
    dst = np.asarray(dst, np.int32)
    rngw = np.random.RandomState(11)
    W, bias = rngw.randn(F, F) * 0.3, rngw.randn(F) * 0.1

    def make():
        eng = gw.GnnSummaryEngine(eb, vb, feature_dim=F)
        eng.set_weights(W, bias)
        eng.load_feature_units(gw.default_features(vb, F, seed=3))
        return eng

    oracle = make()
    baseline = oracle.process(src, dst)
    oracle_slab = oracle.state()

    gdir = os.path.join(workdir, "gnn")
    os.makedirs(gdir, exist_ok=True)
    ckpt = os.path.join(gdir, "gnn.npz")
    eng = make()
    eng.enable_wal(gdir, tenant="gnn")
    eng.enable_auto_checkpoint(ckpt, every_n_windows=2)
    call_w = 4
    fired = []
    out = []
    plans = {
        1: [faults.FaultSpec(site="dispatch", on_call=1, fatal=True)],
    }
    killed_at = None
    for call, lo in enumerate(range(0, num_w, call_w)):
        s = src[lo * eb:(lo + call_w) * eb]
        d = dst[lo * eb:(lo + call_w) * eb]
        try:
            with faults.inject(*plans.get(call, [])) as plan:
                out += eng.process(s, d)
            fired += list(plan.fired)
        except faults.InjectedFault:
            fired += list(plan.fired)
            killed_at = call
            break
    if killed_at is None:
        raise SystemExit("chaos GNN leg: the kill never fired")
    eng2 = make()
    eng2.enable_wal(gdir, tenant="gnn")
    if not eng2.try_resume(ckpt):
        raise SystemExit("chaos GNN leg: no resumable checkpoint "
                         "after the kill")
    resumed_from = eng2.resume_offset() // eb
    # resume_and_replay reloads the checkpoint itself, so the probe
    # above cost nothing; the killed call's edges were journaled
    # BEFORE the fold died, so the replay reproduces them
    replayed = eng2.resume_and_replay(ckpt)
    off = eng2.resume_offset()
    rest = eng2.process(src[off:], dst[off:]) if off < num_w * eb \
        else []
    final = out[:resumed_from] + replayed + rest
    if final != baseline:
        raise SystemExit("chaos GNN leg: summaries DIVERGED from the "
                         "fault-free run")
    if not np.array_equal(eng2.state(), oracle_slab):
        raise SystemExit("chaos GNN leg: feature slab DIVERGED from "
                         "the fault-free oracle")
    return {
        "windows": num_w,
        "feature_dim": F,
        "killed_at_call": killed_at,
        "resumed_from_window": resumed_from,
        "replayed_windows": len(replayed),
        "faults_fired": [list(f) for f in fired],
        "parity": True,
    }


def leg_autotune(path: str, eb: int, num_w: int, workdir: str) -> dict:
    """The autotune leg: the driver's SCAN tier with the online tuner
    live (GS_AUTOTUNE=1, hermetic cache in the workdir), killed
    mid-stream and resumed — proving (a) results stay bit-identical to
    the fault-free tuned run, and (b) the TUNING STATE round-trips the
    checkpoint: the resumed driver's tuner state equals what the
    checkpoint carried, so a resumed stream keeps its learned
    configuration instead of re-exploring from scratch."""
    from gelly_streaming_tpu.utils import checkpoint as ckpt_mod

    env_prev = {k: os.environ.get(k)
                for k in ("GS_AUTOTUNE", "GS_TUNE_CACHE",
                          "GS_STAGE_TIMEOUT_S")}
    os.environ["GS_AUTOTUNE"] = "1"
    os.environ["GS_TUNE_CACHE"] = workdir
    # this leg proves the tuning-state round-trip, not the watchdog
    # (leg A owns that): the chaos 1 s deadline would demote the scan
    # tier under host load and leave the tuner measuring nothing
    os.environ["GS_STAGE_TIMEOUT_S"] = "30"
    piece = 1 << 20

    def make():
        return StreamingAnalyticsDriver(
            window_ms=0, edge_bucket=eb, vertex_bucket=1024,
            analytics=("degrees", "cc", "bipartite", "triangles"),
            snapshot_tier="scan")

    try:
        baseline = [
            _digest(r)
            for r in make().stream_file(path, chunk_bytes=piece)]
        assert len(baseline) == num_w, (len(baseline), num_w)

        ckpt = os.path.join(workdir, "autotune.npz")
        drv = make()
        drv.enable_auto_checkpoint(ckpt, every_n_windows=4)
        got = {}
        killed = False
        try:
            with faults.inject(faults.FaultSpec(
                    site="dispatch", on_call=6, fatal=True)) as plan:
                for r in drv.stream_file(path, chunk_bytes=piece):
                    got[_digest(r)[0]] = _digest(r)
        except faults.InjectedFault:
            killed = True
        if not killed:
            raise SystemExit("chaos autotune leg: the kill never "
                             "fired (fired=%r)" % (plan.fired,))

        drv2 = make()
        if not drv2.try_resume(ckpt):
            raise SystemExit("chaos autotune leg: no resumable "
                             "checkpoint after the kill")
        # the tuning state must have ridden the checkpoint bit-for-bit
        saved_state, _used = ckpt_mod.load_latest(ckpt)
        if "autotune" not in saved_state:
            raise SystemExit("chaos autotune leg: checkpoint carries "
                             "no autotune state")
        restored = drv2._scan_tuner.state_dict()
        if restored != saved_state["autotune"]:
            raise SystemExit(
                "chaos autotune leg: resumed tuner state diverged "
                "from the checkpointed one:\n%r\nvs\n%r"
                % (restored, saved_state["autotune"]))
        if int(restored.get("round", 0)) < 1:
            raise SystemExit(
                "chaos autotune leg: the tuner never recorded a "
                "round before the checkpoint — the leg is not "
                "exercising the scheduler (demoted tier? deadline?)")
        resumed_from = drv2.windows_done
        for r in drv2.stream_file(path, chunk_bytes=piece,
                                  resume=resumed_from > 0):
            got[_digest(r)[0]] = _digest(r)
        final = [got[k] for k in sorted(got)]
        if final != baseline:
            raise SystemExit(
                "chaos autotune leg DIVERGED from the fault-free run")
        return {
            "windows": num_w,
            "resumed_from_window": resumed_from,
            "tuner_rounds_at_resume": int(restored.get("round", 0)),
            "tuner_incumbent": restored.get("incumbent"),
            "parity": True,
        }
    finally:
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def leg_resident(path: str, eb: int, num_w: int, workdir: str) -> dict:
    """The resident-tier leg: the driver pinned to the RESIDENT
    megakernel (ops/resident_engine — donated super-batch programs +
    the ingest ring), killed by a fatal injected fault MID-SUPERBATCH
    and resumed from its auto-checkpoint — the final window-by-window
    sha256 digests must equal the fault-free SCAN-tier oracle, so the
    donated carry provably never leaks a half-applied super-batch into
    delivered results (checkpoints are gathered at super-batch
    boundaries only). Runs with a 30 s stage deadline like the
    autotune leg: this leg proves the kill→resume parity of the
    resident tier, not the watchdog (leg A owns that), and the chaos
    1 s deadline would demote the megakernel under host load."""
    env_prev = {k: os.environ.get(k) for k in ("GS_STAGE_TIMEOUT_S",)}
    os.environ["GS_STAGE_TIMEOUT_S"] = "30"
    piece = 1 << 20

    def make(tier):
        return StreamingAnalyticsDriver(
            window_ms=0, edge_bucket=eb, vertex_bucket=1024,
            analytics=("degrees", "cc", "bipartite", "triangles"),
            snapshot_tier=tier)

    try:
        # the ORACLE is the scan tier: cross-tier parity is the claim
        baseline = [
            _digest(r)
            for r in make("scan").stream_file(path, chunk_bytes=piece)]
        assert len(baseline) == num_w, (len(baseline), num_w)

        ckpt = os.path.join(workdir, "resident.npz")
        drv = make("resident")
        drv.enable_auto_checkpoint(ckpt, every_n_windows=4)
        got = {}
        killed = False
        fired = []
        try:
            with faults.inject(faults.FaultSpec(
                    site="dispatch", on_call=3, fatal=True)) as plan:
                for r in drv.stream_file(path, chunk_bytes=piece):
                    got[_digest(r)[0]] = _digest(r)
        except faults.InjectedFault:
            killed = True
            fired = list(plan.fired)
        if not killed:
            raise SystemExit("chaos resident leg: the kill never "
                             "fired (fired=%r)" % (plan.fired,))

        drv2 = make("resident")
        if not drv2.try_resume(ckpt):
            drv2 = make("resident")  # killed before the first flush
        resumed_from = drv2.windows_done
        for r in drv2.stream_file(path, chunk_bytes=piece,
                                  resume=resumed_from > 0):
            got[_digest(r)[0]] = _digest(r)
        final = [got[k] for k in sorted(got)]
        if final != baseline:
            raise SystemExit("chaos resident leg DIVERGED from the "
                             "fault-free scan-tier oracle")
        return {
            "windows": num_w,
            "resumed_from_window": resumed_from,
            "faults_fired": [list(f) for f in fired],
            "parity": True,
        }
    finally:
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def leg_tenancy(workdir: str) -> dict:
    """The multi-tenant drill (core/tenancy.py): N tenants through
    the vmapped cohort with per-tenant auto-checkpoints, taking

      · ONE tenant's slab prep poisoned mid-cohort (injected
        `tenant_prep` raise) → that tenant demotes ALONE to its
        single-tenant engine (utils/resilience records it with the
        tenant label) while the cohort keeps dispatching the others
      · a FATAL kill mid-dispatch (`cohort_dispatch`) → a fresh
        cohort resumes every tenant from its OWN checkpoint
        (resume_all) and re-feeds from each resume_offset

    and the final per-tenant summary stream must be BIT-IDENTICAL to
    the fault-free sequential single-tenant oracle — single-tenant
    fault isolation AND per-tenant kill→resume, on one schedule."""
    import numpy as np

    from gelly_streaming_tpu.core.tenancy import TenantCohort

    # like the autotune/resident legs: this leg proves isolation and
    # kill→resume, not the watchdog (leg A owns that) — the chaos 1 s
    # deadline would cut the cohort program's cold compile under load
    env_prev = os.environ.get("GS_STAGE_TIMEOUT_S")
    os.environ["GS_STAGE_TIMEOUT_S"] = "30"
    try:
        return _leg_tenancy_body(workdir, np, TenantCohort)
    finally:
        if env_prev is None:
            os.environ.pop("GS_STAGE_TIMEOUT_S", None)
        else:
            os.environ["GS_STAGE_TIMEOUT_S"] = env_prev


def _leg_tenancy_body(workdir: str, np, TenantCohort) -> dict:
    eb, vb, n_tenants, num_w = 512, 1024, 4, 8
    streams = {}
    for i in range(n_tenants):
        n = num_w * eb - (eb // 3 if i == 3 else 0)
        s, d = make_stream(n, vb, seed=40 + i)
        streams["t%d" % i] = (s.astype(np.int32), d.astype(np.int32))

    # fault-free oracle: N sequential single-tenant engines
    oracle = {}
    for tid, (s, d) in streams.items():
        oracle[tid] = StreamSummaryEngine(
            edge_bucket=eb, vertex_bucket=vb).process(s, d)

    ckdir = os.path.join(workdir, "tenants")

    def make():
        co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        for tid in streams:
            co.admit(tid)
        co.enable_auto_checkpoint(ckdir, every_n_windows=2)
        return co

    co = make()
    got = {tid: [] for tid in streams}
    cursors = {tid: 0 for tid in streams}
    fired = []
    killed = False
    # tenant_prep fires once per tenant per round (sorted tids):
    # on_call=6 is round 2, tenant index 1 → "t1" demotes; the fatal
    # cohort_dispatch on_call=4 kills round 4's vmapped dispatch
    plan_specs = [
        faults.FaultSpec(site="tenant_prep", on_call=6),
        faults.FaultSpec(site="cohort_dispatch", on_call=4,
                         fatal=True),
    ]
    try:
        with faults.inject(*plan_specs) as plan:
            live = True
            while live:
                live = False
                for tid, (s, d) in streams.items():
                    c = cursors[tid]
                    if c >= len(s):
                        continue
                    co.feed(tid, s[c:c + eb], d[c:c + eb])
                    cursors[tid] = min(len(s), c + eb)
                    live = True
                for tid, res in co.pump().items():
                    got[tid].extend(res)
    except faults.InjectedFault:
        killed = True
        fired = list(plan.fired)
    if not killed:
        raise SystemExit("chaos tenancy leg: the kill never fired "
                         "(fired=%r)" % (plan.fired,))
    demoted = [tid for tid in streams
               if co.tenant_tier(tid) == "single"]
    if demoted != ["t1"]:
        raise SystemExit("chaos tenancy leg: expected exactly t1 "
                         "demoted before the kill, got %r" % demoted)
    tenant_demotions = [e for e in resilience.demotion_events()
                        if e.get("tenant") == "t1"
                        and e["from"] == "cohort"
                        and e["to"] == "single"]
    if not tenant_demotions:
        raise SystemExit("chaos tenancy leg: no tenant-labeled "
                         "demotion event was recorded")

    # the simulated process death: a FRESH cohort resumes every
    # tenant from its own checkpoint and re-feeds from its offset
    co2 = make()
    resumed = co2.resume_all()
    if not any(resumed.values()):
        raise SystemExit("chaos tenancy leg: no tenant had a "
                         "resumable checkpoint after the kill")
    final = {}
    for tid, (s, d) in streams.items():
        off = co2.resume_offset(tid)
        r = off // eb
        if len(got[tid]) < r:
            raise SystemExit(
                "chaos tenancy leg: tenant %s checkpoint covers %d "
                "windows but only %d were delivered pre-kill — the "
                "staged-checkpoint delivery contract broke" %
                (tid, r, len(got[tid])))
        final[tid] = got[tid][:r]
        c = off
        while c < len(s):
            co2.feed(tid, s[c:c + 2 * eb], d[c:c + 2 * eb])
            c = min(len(s), c + 2 * eb)
            for t2, res in co2.pump().items():
                if t2 == tid:
                    final[tid].extend(res)
        final[tid].extend(co2.close(tid))
    for tid in streams:
        if final[tid] != oracle[tid]:
            raise SystemExit(
                "chaos tenancy leg DIVERGED from the fault-free "
                "sequential oracle for tenant %s (%d vs %d windows)"
                % (tid, len(final[tid]), len(oracle[tid])))
    return {
        "tenants": n_tenants,
        "windows_per_tenant": num_w,
        "demoted_tenant": "t1",
        "resumed": {tid: bool(v) for tid, v in sorted(
            resumed.items())},
        "faults_fired": [list(f) for f in fired],
        "parity": True,
    }


def leg_provenance(workdir: str) -> dict:
    """The provenance-ledger leg (utils/provenance.py): a fully armed
    cohort (provenance + WAL + per-tenant checkpoints) killed fatally
    mid-dispatch → fresh cohort recovers (checkpoint resume + WAL
    suffix replay) → the recovered run's provenance records —
    INCLUDING the re-emitted ones for replayed windows — must be
    byte-identical to a fault-free oracle run's ledger, record for
    record. The audit trail is only an audit trail if a crash cannot
    fork it: at-least-once re-emission must reproduce the exact
    payload bytes (no timestamps, no process identity, path knobs
    excluded from the fingerprint), so consumers dedup by
    (tenant, window, tier) and never see two histories."""
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.utils import provenance

    env_prev = {k: os.environ.get(k)
                for k in ("GS_STAGE_TIMEOUT_S", "GS_PROVENANCE",
                          "GS_PROVENANCE_DIR")}
    os.environ["GS_STAGE_TIMEOUT_S"] = "30"
    os.environ["GS_PROVENANCE"] = "1"
    try:
        return _leg_provenance_body(workdir, TenantCohort, provenance)
    finally:
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _leg_provenance_body(workdir, TenantCohort, provenance) -> dict:
    eb, vb, n_tenants, num_w = 512, 1024, 3, 6
    streams = {}
    for i in range(n_tenants):
        s, d = make_stream(num_w * eb, vb, seed=70 + i)
        streams["p%d" % i] = (s.astype(np.int32), d.astype(np.int32))

    def run(prov_dir, cohort_fn):
        os.environ["GS_PROVENANCE_DIR"] = prov_dir
        got = cohort_fn()
        sc = provenance.scan(prov_dir)
        if sc["torn"] is not None:
            raise SystemExit("chaos provenance leg: torn ledger tail "
                             "in a completed run: %r" % sc["torn"])
        keyed = {}
        dups = 0
        for rec in sc["records"]:
            key = (rec["tenant"], rec["window"], rec["tier"])
            if key in keyed:
                dups += 1
                if keyed[key] != rec:
                    raise SystemExit(
                        "chaos provenance leg: re-emitted record %r "
                        "is NOT byte-identical to its first emission"
                        % (key,))
            keyed[key] = rec
        return got, keyed, dups

    # fault-free oracle: same streams, clean pump, its own ledger
    def oracle_run():
        co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        got = {tid: [] for tid in streams}
        for tid in streams:
            co.admit(tid)
        for tid, (s, d) in streams.items():
            co.feed(tid, s, d)
        for tid, res in co.pump().items():
            got[tid].extend(res)
        return got

    odir = os.path.join(workdir, "prov_oracle")
    oracle, orecs, _ = run(odir, oracle_run)

    # chaos run: armed the same way + WAL + checkpoints, killed
    # fatally mid-dispatch, recovered into a FRESH cohort
    cdir = os.path.join(workdir, "prov_chaos")
    wdir = os.path.join(workdir, "prov_wal")
    kdir = os.path.join(workdir, "prov_ckpt")
    fired = []
    state = {"replayed": 0}

    def chaos_run():
        co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        for tid in streams:
            co.admit(tid)
        if not co.enable_wal(wdir):
            raise SystemExit("chaos provenance leg: WAL refused")
        co.enable_auto_checkpoint(kdir, every_n_windows=2)
        got = {tid: [] for tid in streams}
        cursors = {tid: 0 for tid in streams}
        killed = False
        try:
            with faults.inject(faults.FaultSpec(
                    site="cohort_dispatch", on_call=2,
                    fatal=True)) as plan:
                live = True
                while live:
                    live = False
                    for tid, (s, d) in streams.items():
                        c = cursors[tid]
                        if c >= len(s):
                            continue
                        co.feed(tid, s[c:c + eb], d[c:c + eb])
                        cursors[tid] = min(len(s), c + eb)
                        live = True
                    for tid, res in co.pump().items():
                        got[tid].extend(res)
        except faults.InjectedFault:
            killed = True
            fired.extend(plan.fired)
        if not killed:
            raise SystemExit("chaos provenance leg: the kill never "
                             "fired (fired=%r)" % (plan.fired,))
        # the simulated process death: recovery replays the WAL
        # suffix past each tenant's checkpoint — the re-pumped
        # windows RE-EMIT their provenance records
        co2 = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        co2.enable_auto_checkpoint(kdir, every_n_windows=2)
        co2.enable_wal(wdir)
        rec = co2.recover()
        state["replayed"] = sum(rec["replayed_edges"].values()) \
            if isinstance(rec.get("replayed_edges"), dict) \
            else int(bool(rec))
        # truncate every tenant to its checkpoint coverage FIRST —
        # pump() delivers ready windows for ANY tenant, not only the
        # one just fed, so final must be fully keyed before pumping
        final = {tid: got[tid][:co2.resume_offset(tid) // eb]
                 for tid in streams}
        for tid, (s, d) in streams.items():
            c = cursors[tid]
            while c < len(s):
                co2.feed(tid, s[c:c + eb], d[c:c + eb])
                c = min(len(s), c + eb)
        for t2, res in co2.pump().items():
            final[t2].extend(res)
        return final

    final, crecs, dups = run(cdir, chaos_run)
    for tid in streams:
        if final[tid] != oracle[tid]:
            raise SystemExit("chaos provenance leg: summaries "
                             "DIVERGED from the fault-free run for "
                             "tenant %s" % tid)
    if crecs != orecs:
        only_o = sorted(set(orecs) - set(crecs))[:4]
        only_c = sorted(set(crecs) - set(orecs))[:4]
        diff = [k for k in orecs if k in crecs
                and orecs[k] != crecs[k]][:4]
        raise SystemExit(
            "chaos provenance leg: recovered ledger is NOT "
            "record-identical to the fault-free oracle's "
            "(missing=%r extra=%r differing=%r)"
            % (only_o, only_c, diff))
    if dups == 0:
        raise SystemExit("chaos provenance leg: recovery re-emitted "
                         "no records — the replay never exercised "
                         "at-least-once re-emission")
    return {
        "tenants": n_tenants,
        "windows_per_tenant": num_w,
        "records": len(orecs),
        "re_emitted": dups,
        "replayed": state["replayed"],
        "knob_fingerprint": provenance.knob_fingerprint(),
        "faults_fired": [list(f) for f in fired],
        "parity": True,
    }


def _summaries_digest(summaries) -> str:
    import hashlib

    h = hashlib.sha256()
    for s in summaries:
        h.update(json.dumps(s, sort_keys=True).encode())
    return h.hexdigest()[:16]


def _ledger_has(name: str) -> bool:
    path = telemetry.ledger_path()
    if path is None or not os.path.exists(path):
        return False
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("t") == "event" and rec.get("name") == name:
                return True
    return False


def leg_serve(workdir: str) -> dict:
    """The durable-serving drill (core/serve.py + utils/wal.py), four
    sub-legs on one schedule:

      · KILL mid-window: two tenants fed through a real loopback
        socket into a journal-armed server; a fatal `cohort_dispatch`
        fault kills the pump mid-round. A FRESH cohort recovers
        (checkpoint resume + WAL suffix replay), serving continues,
        and the final per-tenant summary streams are bit-identical to
        the fault-free direct-feed oracle — exactly-once window
        results under a kill at an arbitrary point.
      · TORN TAIL: the journal's final record is physically truncated
        (the shape an in-flight crash tears). Recovery falls back
        exactly one record with a durable `wal_torn_tail` event; the
        producer re-sends its un-acknowledged tail and parity holds.
      · SLOW CLIENT: a response send stalled past GS_SERVE_IDLE_S is
        SHED (durable `serve_client_shed`) and the pump keeps serving
        other connections — a stalled reader can never wedge ingest.
      · GRACEFUL DRAIN: a standalone server subprocess takes SIGTERM
        during active ingest and exits 0 with every accepted window
        finalized in its results file (drain digest ≡ keep-running
        digest) and a SEALED journal.
    """
    import numpy as np

    from gelly_streaming_tpu.core.serve import (ServeClient,
                                                StreamServer)
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.utils import wal as wal_mod

    eb, vb, num_w = 512, 1024, 8
    streams = {}
    for i in range(2):
        s, d = make_stream(num_w * eb, vb, seed=60 + i)
        streams["s%d" % i] = (s.astype(np.int32), d.astype(np.int32))

    # fault-free oracle: the direct cohort feed
    oracle = {}
    co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    for tid in streams:
        co.admit(tid)
    for w in range(num_w):
        for tid, (s, d) in streams.items():
            co.feed(tid, s[w * eb:(w + 1) * eb],
                    d[w * eb:(w + 1) * eb])
        for tid, res in co.pump().items():
            oracle.setdefault(tid, []).extend(res)
    for tid in streams:
        oracle[tid].extend(co.close(tid))

    env_prev = os.environ.get("GS_STAGE_TIMEOUT_S")
    os.environ["GS_STAGE_TIMEOUT_S"] = "30"
    try:
        out = {
            "kill": _serve_kill_subleg(workdir, np, StreamServer,
                                       ServeClient, TenantCohort,
                                       wal_mod, streams, oracle, eb,
                                       vb, num_w),
            "torn_tail": _serve_torn_subleg(workdir, np, TenantCohort,
                                            wal_mod, eb, vb),
            "slow_client": _serve_slow_subleg(workdir, np,
                                              StreamServer,
                                              ServeClient,
                                              TenantCohort, eb, vb),
            "drain": _serve_drain_subleg(workdir, np, streams,
                                         oracle, eb, vb, num_w),
        }
    finally:
        if env_prev is None:
            os.environ.pop("GS_STAGE_TIMEOUT_S", None)
        else:
            os.environ["GS_STAGE_TIMEOUT_S"] = env_prev
    out["parity"] = all(v.get("parity") for v in out.values())
    if not out["parity"]:
        raise SystemExit("chaos serve leg DIVERGED: %r" % out)
    return out


def _serve_kill_subleg(workdir, np, StreamServer, ServeClient,
                       TenantCohort, wal_mod, streams, oracle, eb,
                       vb, num_w) -> dict:
    wal_dir = os.path.join(workdir, "serve_wal")
    ck_dir = os.path.join(workdir, "serve_ckpt")

    cohort = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    assert cohort.enable_wal(wal_dir)
    cohort.enable_auto_checkpoint(ck_dir, every_n_windows=2)
    server = StreamServer(cohort, port=0).start()
    cli = ServeClient(server.port, timeout=60)
    got = {tid: {} for tid in streams}
    fired, killed, killed_at = [], False, None

    def take(results):
        for tid, rows in results.items():
            for row in rows:
                got[tid][row["window"]] = row["summary"]

    try:
        with faults.inject(faults.FaultSpec(
                site="cohort_dispatch", on_call=4,
                fatal=True)) as plan:
            for tid in sorted(streams):
                assert cli.admit(tid)["ok"]
            for w in range(num_w):
                for tid, (s, d) in sorted(streams.items()):
                    r = cli.feed(tid, s[w * eb:(w + 1) * eb],
                                 d[w * eb:(w + 1) * eb])
                    assert r["ok"], r
                take(cli.pump()["results"])
    except (ConnectionError, OSError):
        killed = True
        killed_at = w
        fired = list(plan.fired)
    cli.close()
    server.close()
    if not killed or not server.fatal:
        raise SystemExit("chaos serve leg: the kill never fired "
                         "(fired=%r)" % (plan.fired,))

    # restart: fresh cohort, checkpoint resume + WAL suffix replay
    co2 = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    assert co2.enable_wal(wal_dir)
    co2.enable_auto_checkpoint(ck_dir, every_n_windows=2)
    rec = co2.recover()
    if not any(rec["resumed"].values()):
        raise SystemExit("chaos serve leg: no tenant resumed a "
                         "checkpoint after the kill")
    if not _ledger_has("wal_replayed"):
        raise SystemExit("chaos serve leg: no durable wal_replayed "
                         "event in the ledger")
    s2 = StreamServer(co2, port=0).start()
    cli2 = ServeClient(s2.port, timeout=60)
    take(cli2.pump()["results"])  # the replayed suffix's windows
    for w in range(killed_at + 1, num_w):
        for tid, (s, d) in sorted(streams.items()):
            assert cli2.feed(tid, s[w * eb:(w + 1) * eb],
                             d[w * eb:(w + 1) * eb])["ok"]
        take(cli2.pump()["results"])
    for tid in sorted(streams):
        take({tid: cli2.close_tenant(tid)["results"]})
    cli2.close()
    s2.close()
    final = {tid: [got[tid][k] for k in sorted(got[tid])]
             for tid in streams}
    for tid in streams:
        if final[tid] != oracle[tid]:
            raise SystemExit(
                "chaos serve leg DIVERGED from the fault-free oracle "
                "for tenant %s (%d vs %d windows)"
                % (tid, len(final[tid]), len(oracle[tid])))
    return {
        "parity": True,
        "killed_at_window": killed_at,
        "replayed_edges": rec["replayed_edges"],
        "faults_fired": [list(f) for f in fired],
        "digests": {tid: _summaries_digest(final[tid])
                    for tid in sorted(streams)},
    }


def _serve_torn_subleg(workdir, np, TenantCohort, wal_mod, eb,
                       vb) -> dict:
    wal_dir = os.path.join(workdir, "torn_wal")
    s, d = make_stream(3 * eb, vb, seed=70)
    s, d = s.astype(np.int32), d.astype(np.int32)
    oracle = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    oracle.admit("t")
    oracle.feed("t", s, d)
    want = oracle.pump()["t"]

    co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    assert co.enable_wal(wal_dir)
    co.admit("t")
    for w in range(3):  # three journal records, never pumped
        co.feed("t", s[w * eb:(w + 1) * eb], d[w * eb:(w + 1) * eb])
    co._wal.close()  # the crash: queues die with the process

    # physical tail damage: the last record loses its final bytes
    seg = sorted(os.path.join(wal_dir, f)
                 for f in os.listdir(wal_dir))[-1]
    with open(seg, "r+b") as f:
        f.seek(0, 2)
        f.truncate(f.tell() - 3)

    co2 = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    assert co2.enable_wal(wal_dir)
    rec = co2.recover()
    replayed = rec["replayed_edges"].get("t", 0)
    if replayed != 2 * eb:
        raise SystemExit(
            "chaos serve torn-tail: expected the replay to fall back "
            "exactly one record (%d edges), got %d"
            % (2 * eb, replayed))
    if not _ledger_has("wal_torn_tail"):
        raise SystemExit("chaos serve torn-tail: no durable "
                         "wal_torn_tail event in the ledger")
    # the producer's un-acked tail is re-sent (its fsync never
    # completed, so it was never acknowledged durable) — parity holds
    co2.feed("t", s[2 * eb:], d[2 * eb:])
    have = co2.pump()["t"]
    if have != want:
        raise SystemExit("chaos serve torn-tail DIVERGED after "
                         "fallback+resend")
    return {"parity": True, "replayed_edges": replayed,
            "dropped_records": 1}


def _serve_slow_subleg(workdir, np, StreamServer, ServeClient,
                       TenantCohort, eb, vb) -> dict:
    prev = os.environ.get("GS_SERVE_IDLE_S")
    os.environ["GS_SERVE_IDLE_S"] = "0.5"
    try:
        co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        server = StreamServer(co, port=0).start()
        s, d = make_stream(2 * eb, vb, seed=71)
        s, d = s.astype(np.int32), d.astype(np.int32)
        slow = ServeClient(server.port, timeout=60)
        assert slow.admit("t")["ok"]
        assert slow.feed("t", s[:eb], d[:eb])["ok"]
        shed = False
        with faults.inject(faults.FaultSpec(
                site="serve_send", on_call=1, action="hang",
                seconds=2.0)):
            try:
                slow.pump()  # this response's send stalls → shed
                raise SystemExit("chaos serve slow-client: the stall "
                                 "never shed the connection")
            except (ConnectionError, OSError):
                shed = True
        if not _ledger_has("serve_client_shed"):
            raise SystemExit("chaos serve slow-client: no durable "
                             "serve_client_shed event")
        # the pump is NOT wedged: a fresh connection still serves
        cli = ServeClient(server.port, timeout=60)
        assert cli.feed("t", s[eb:], d[eb:])["ok"]
        windows = len(cli.pump()["results"].get("t", []))
        cli.close()
        slow.close()
        server.close()
        if windows < 1:
            raise SystemExit("chaos serve slow-client: the pump "
                             "served nothing after the shed")
        return {"parity": True, "shed": shed,
                "windows_after_shed": windows}
    finally:
        if prev is None:
            os.environ.pop("GS_SERVE_IDLE_S", None)
        else:
            os.environ["GS_SERVE_IDLE_S"] = prev


def _serve_drain_subleg(workdir, np, streams, oracle, eb, vb,
                        num_w) -> dict:
    import signal
    import subprocess
    import time

    from gelly_streaming_tpu.core.serve import ServeClient
    from gelly_streaming_tpu.utils import wal as wal_mod

    wal_dir = os.path.join(workdir, "drain_wal")
    results = os.path.join(workdir, "drain_results.jsonl")
    port_file = os.path.join(workdir, "drain_port.txt")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "gelly_streaming_tpu.core.serve",
         "--edge-bucket", str(eb), "--vertex-bucket", str(vb),
         "--port", "0", "--port-file", port_file,
         "--wal", wal_dir,
         "--ckpt", os.path.join(workdir, "drain_ckpt"),
         "--ckpt-every", "2", "--results", results],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    t0 = time.monotonic()
    while not os.path.exists(port_file):
        if proc.poll() is not None or time.monotonic() - t0 > 120:
            raise SystemExit("chaos serve drain: server never came "
                             "up:\n%s"
                             % proc.communicate()[0].decode()[-2000:])
        time.sleep(0.05)
    with open(port_file) as f:
        port = int(f.read().strip())
    cli = ServeClient(port, timeout=60)
    for tid in sorted(streams):
        assert cli.admit(tid)["ok"]
    for w in range(num_w):
        for tid, (s, d) in sorted(streams.items()):
            assert cli.feed(tid, s[w * eb:(w + 1) * eb].tolist(),
                            d[w * eb:(w + 1) * eb].tolist())["ok"]
    # SIGTERM lands while the last feeds are still queued/un-pumped —
    # the graceful drain must finalize them, not lose them
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=180)
    cli.close()
    if proc.returncode != 0:
        raise SystemExit("chaos serve drain: exit %d, want 0:\n%s"
                         % (proc.returncode, out.decode()[-2000:]))
    got = {}
    with open(results) as f:
        for line in f:
            row = json.loads(line)
            got.setdefault(row["tenant"], {})[row["window"]] \
                = row["summary"]
    final = {tid: [got[tid][k] for k in sorted(got[tid])]
             for tid in got}
    # the drain digest must equal the keep-running digest: every
    # accepted window finalized, none lost (close() is not part of
    # this schedule — full windows only, so the streams compare flat)
    for tid in streams:
        if final.get(tid) != oracle[tid]:
            raise SystemExit(
                "chaos serve drain DIVERGED for tenant %s: %d "
                "windows vs %d" % (tid, len(final.get(tid, [])),
                                   len(oracle[tid])))
    info = wal_mod.scan(wal_dir)
    if not info["sealed"]:
        raise SystemExit("chaos serve drain: journal not sealed")
    return {"parity": True, "rc": proc.returncode, "sealed": True,
            "digest_match": True,
            "windows": {tid: len(v) for tid, v in final.items()}}


def leg_latency(workdir: str) -> dict:
    """The latency-plane drill (utils/latency.py, GS_LATENCY=1):

      · a journal-armed cohort is fed with the plane armed (admission
        stamps ride the WAL ts column), then crashes before pumping;
      · a FRESH cohort (fresh plane — the new-process shape) recovers
        and pumps: every replayed window's record must carry
        `replayed=True` and an end-to-end latency AT LEAST the
        crash→recovery gap — the admission timestamp survived the
        kill instead of resetting to zero;
      · each record's stage waterfall still sums to its end-to-end
        (the conservation contract), and the armed summaries are
        digest-identical to the fault-free disarmed oracle.
    """
    import time

    import numpy as np

    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.utils import latency

    eb, vb, num_w = 512, 1024, 4
    s, d = make_stream(num_w * eb, vb, seed=90)
    s, d = s.astype(np.int32), d.astype(np.int32)

    # the suite-global 1s deadline (KNOBS) belongs to the timeout
    # legs: this leg's contract is stamp preservation, and its first
    # dispatch may carry a cold compile depending on which legs ran
    # before it — give it the same 30s guard its siblings take
    env_prev = os.environ.get("GS_STAGE_TIMEOUT_S")
    os.environ["GS_STAGE_TIMEOUT_S"] = "30"

    oracle = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    oracle.admit("t")
    oracle.feed("t", s, d)
    want = [_summaries_digest(oracle.pump()["t"])]

    wal_dir = os.path.join(workdir, "latency_wal")
    gap_s = 0.25
    prev = os.environ.get("GS_LATENCY")
    os.environ["GS_LATENCY"] = "1"
    try:
        latency.reset()
        co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        assert co.enable_wal(wal_dir)
        co.admit("t")
        co.feed("t", s, d)
        co._wal.close()  # the crash: queues die with the process
        time.sleep(gap_s)

        latency.reset()  # the new process starts a fresh plane
        co2 = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        assert co2.enable_wal(wal_dir)
        co2.recover()
        got = co2.pump()["t"]
        recs = latency.recent()
        if len(recs) != num_w:
            raise SystemExit(
                "chaos latency leg: %d window records, want %d"
                % (len(recs), num_w))
        floor = min(r["e2e_s"] for r in recs)
        preserved = all(r["replayed"] for r in recs) \
            and floor >= gap_s
        if not preserved:
            raise SystemExit(
                "chaos latency leg: replayed windows lost their "
                "admission stamps (min e2e %.3fs < %.3fs gap, "
                "replayed=%s)" % (floor, gap_s,
                                  [r["replayed"] for r in recs]))
        for r in recs:
            ok, gap = latency.reconcile(r)
            if not ok:
                raise SystemExit(
                    "chaos latency leg: replayed window %s does not "
                    "reconcile (gap %.6fs of %.6fs e2e)"
                    % (r["window"], gap, r["e2e_s"]))
        if [_summaries_digest(got)] != want:
            raise SystemExit("chaos latency leg DIVERGED from the "
                             "disarmed fault-free oracle")
    finally:
        if prev is None:
            os.environ.pop("GS_LATENCY", None)
        else:
            os.environ["GS_LATENCY"] = prev
        if env_prev is None:
            os.environ.pop("GS_STAGE_TIMEOUT_S", None)
        else:
            os.environ["GS_STAGE_TIMEOUT_S"] = env_prev
        latency.reset()
    return {
        "parity": True,
        "preserved": True,
        "replayed_windows": len(recs),
        "min_replay_latency_s": round(floor, 3),
        "crash_gap_s": gap_s,
    }


def leg_poison(workdir: str) -> dict:
    """The poison-input drill (utils/sanitize + the core/tenancy
    bulkhead, GS_SANITIZE=on): an 8-tenant cohort with ONE hostile
    tenant flooding garbage — byte soup through
    native.parse_edge_bytes, out-of-range/negative/overflowing ids,
    and a dispatch poison riding its batches.

      · the 7 healthy tenants' per-tenant summary digests stay
        BIT-IDENTICAL to the fault-free oracle while the bulkhead
        bisects the failing dispatch to the hostile tenant and
        quarantines it (durable `quarantine` event);
      · every rejected edge is recoverable from the dead-letter
        journal — counts AND (offset, src, dst) content reconcile
        against a pure-Python policy oracle;
      · a standalone serve subprocess fed the same hostile mix over a
        real loopback socket drains on SIGTERM with exit 0, healthy
        digests intact, and its DLQ depth equal to the sum of the
        typed `rejected` counts its feed replies carried.
    """
    import numpy as np

    from gelly_streaming_tpu import native
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.utils import sanitize
    from tools.poison_smoke import (EB, VB, hostile_bytes,
                                    oracle_filter)

    eb, vb, num_w, n_tenants = EB, VB, 4, 8
    hostile = "t7"
    streams = {}
    for i in range(n_tenants):
        tid = "t%d" % i
        s, d = make_stream(num_w * eb, vb, seed=130 + i)
        streams[tid] = (s.astype(np.int64), d.astype(np.int64))
    oracle = {}
    for tid, (s, d) in streams.items():
        if tid != hostile:
            oracle[tid] = StreamSummaryEngine(
                edge_bucket=eb, vertex_bucket=vb).process(s, d)

    dlq_dir = os.path.join(workdir, "poison_dlq")
    prev = {k: os.environ.get(k)
            for k in ("GS_SANITIZE", "GS_DLQ_DIR")}
    os.environ["GS_SANITIZE"] = "on"
    os.environ["GS_DLQ_DIR"] = dlq_dir
    try:
        sanitize.reset()
        cohort = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        for tid in streams:
            cohort.admit(tid)

        def poison(payload):
            if payload and hostile in payload:
                raise faults.InjectedFault(
                    "hostile tenant poisons the dispatch",
                    "cohort_dispatch")
            return payload

        hostile_rng = np.random.default_rng(77)
        expected = []
        hoff = 0
        got = {}
        with faults.inject(faults.FaultSpec(
                site="cohort_dispatch", action="call", fn=poison,
                times=10 ** 6)) as plan:
            for w in range(num_w):
                for tid, (s, d) in sorted(streams.items()):
                    if tid == hostile:
                        hs, hd, _ts = native.parse_edge_bytes(
                            hostile_bytes(hostile_rng))
                        keep = oracle_filter(hs, hd)
                        for j in np.flatnonzero(~keep):
                            expected.append((hoff + int(j),
                                             int(hs[j]), int(hd[j])))
                        hoff += len(hs)
                        cohort.feed(tid, hs, hd)
                    else:
                        cohort.feed(tid, s[w * eb:(w + 1) * eb],
                                    d[w * eb:(w + 1) * eb])
                for k, v in cohort.pump().items():
                    got.setdefault(k, []).extend(v)
            fired = list(plan.fired)
        quarantined = cohort.quarantined()
        if quarantined != [hostile]:
            raise SystemExit("chaos poison leg: expected exactly %r "
                             "quarantined, got %r"
                             % (hostile, quarantined))
        for tid in sorted(oracle):
            if got.get(tid, []) != oracle[tid]:
                raise SystemExit(
                    "chaos poison leg DIVERGED for healthy tenant %s "
                    "(%d vs %d windows)" % (tid, len(got.get(tid, [])),
                                            len(oracle[tid])))
        quarantine_events = [
            e for e in resilience.demotion_events()
            if e.get("tenant") == hostile and e["to"] == "quarantined"]
        if not quarantine_events:
            raise SystemExit("chaos poison leg: no quarantine "
                             "demotion event was recorded")

        from tools.dlq_report import gather
        info = sanitize.scan(dlq_dir)
        rec = gather(dlq_dir).get(hostile)
        recovered = (set() if rec is None else
                     set(zip(rec[0].tolist(), rec[1].tolist(),
                             rec[2].tolist())))
        dlq_ok = (recovered == set(expected)
                  and info["edges"] == len(expected))
        if not dlq_ok:
            raise SystemExit(
                "chaos poison leg: DLQ holds %d edge(s), oracle "
                "expected %d (content match: %s)"
                % (info["edges"], len(expected),
                   recovered == set(expected)))

        drain = _poison_drain_subleg(workdir, np, streams, oracle,
                                     eb, vb, num_w, hostile)
    finally:
        sanitize.reset()
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return {
        "parity": True,
        "quarantined": quarantined,
        "dlq_recovered": True,
        "dlq_edges": len(expected),
        "faults_fired": [list(f) for f in fired],
        "drain": drain,
    }


def _poison_drain_subleg(workdir, np, streams, oracle, eb, vb,
                         num_w, hostile) -> dict:
    """The serve half: a standalone subprocess armed with
    GS_SANITIZE=on + its own DLQ, fed the hostile mix over a real
    loopback socket, must SIGTERM-drain with exit 0, healthy digests
    ≡ the oracle, and a DLQ depth equal to the sum of the typed
    `rejected` counts the wire replies carried."""
    import signal
    import subprocess
    import time

    from gelly_streaming_tpu.core.serve import ServeClient
    from gelly_streaming_tpu.utils import sanitize

    drain_dlq = os.path.join(workdir, "poison_drain_dlq")
    results = os.path.join(workdir, "poison_results.jsonl")
    port_file = os.path.join(workdir, "poison_port.txt")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["GS_SANITIZE"] = "on"
    env["GS_DLQ_DIR"] = drain_dlq
    proc = subprocess.Popen(
        [sys.executable, "-m", "gelly_streaming_tpu.core.serve",
         "--edge-bucket", str(eb), "--vertex-bucket", str(vb),
         "--port", "0", "--port-file", port_file,
         "--wal", os.path.join(workdir, "poison_wal"),
         "--ckpt", os.path.join(workdir, "poison_ckpt"),
         "--ckpt-every", "2", "--results", results],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    t0 = time.monotonic()
    while not os.path.exists(port_file):
        if proc.poll() is not None or time.monotonic() - t0 > 120:
            raise SystemExit("chaos poison drain: server never came "
                             "up:\n%s"
                             % proc.communicate()[0].decode()[-2000:])
        time.sleep(0.05)
    with open(port_file) as f:
        port = int(f.read().strip())
    cli = ServeClient(port, timeout=60)
    rng = np.random.default_rng(99)
    rejected_total = 0
    for tid in sorted(streams):
        assert cli.admit(tid)["ok"]
    for w in range(num_w):
        for tid, (s, d) in sorted(streams.items()):
            if tid == hostile:
                # garbage over the wire: out-of-range, negative and
                # int32-overflowing ids mixed with valid ones
                hs = rng.integers(-vb, 4 * vb, eb).astype(object)
                hd = rng.integers(0, vb, eb).astype(object)
                hs[::17] = 1 << 40
                r = cli.request(op="feed", tenant=tid,
                                src=[int(x) for x in hs],
                                dst=[int(x) for x in hd])
                if not r.get("ok"):
                    raise SystemExit("chaos poison drain: hostile "
                                     "feed errored: %s" % r)
                rejected_total += int(r.get("rejected", 0))
            else:
                r = cli.feed(tid, s[w * eb:(w + 1) * eb].tolist(),
                             d[w * eb:(w + 1) * eb].tolist())
                if not r.get("ok") or r.get("rejected"):
                    raise SystemExit("chaos poison drain: clean feed "
                                     "rejected: %s" % r)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=180)
    cli.close()
    if proc.returncode != 0:
        raise SystemExit("chaos poison drain: exit %d, want 0:\n%s"
                         % (proc.returncode, out.decode()[-2000:]))
    if rejected_total == 0:
        raise SystemExit("chaos poison drain: the hostile feed was "
                         "never rejected — the sanitizer did not arm")
    got = {}
    with open(results) as f:
        for line in f:
            row = json.loads(line)
            got.setdefault(row["tenant"], {})[row["window"]] \
                = row["summary"]
    for tid in sorted(oracle):
        final = [got.get(tid, {}).get(k)
                 for k in sorted(got.get(tid, {}))]
        if final != oracle[tid]:
            raise SystemExit(
                "chaos poison drain DIVERGED for healthy tenant %s "
                "(%d vs %d windows)"
                % (tid, len(final), len(oracle[tid])))
    info = sanitize.scan(drain_dlq)
    if info["edges"] != rejected_total:
        raise SystemExit(
            "chaos poison drain: DLQ holds %d edge(s) but the wire "
            "replies reported %d rejected — a rejected record went "
            "missing" % (info["edges"], rejected_total))
    from gelly_streaming_tpu.utils import wal as wal_mod

    sealed = wal_mod.scan(os.path.join(workdir, "poison_wal"))["sealed"]
    if not sealed:
        raise SystemExit("chaos poison drain: journal not sealed")
    return {"rc": proc.returncode, "sealed": sealed,
            "digest_match": True, "rejected_edges": rejected_total,
            "dlq_edges": info["edges"]}


def leg_pump(workdir: str) -> dict:
    """The async-pump drill (GS_PUMP=async, core/serve.py): two
    tenants fed through a loopback server whose DEDICATED pump thread
    owns dispatch.

      · OVERLAP: one dispatch is hung mid-run and an ingest batch is
        accepted while it is in flight (overlap_feeds > 0) — the leg
        proves the overlap path, never a quietly serialized pump.
      · KILL mid-pump: a fatal InjectedFault fires INSIDE the pump
        thread (the ingest side keeps acking — the WAL is the only
        survivor). A fresh async server recovers (checkpoint resume +
        WAL suffix replay), the un-acked suffix is re-fed, and the
        union of pre-kill deliveries + post-recovery deliveries is
        bit-identical to the fault-free sync direct-feed oracle —
        at-least-once under a pump-thread death, deduped by window
        ordinal.
    """
    import time

    from gelly_streaming_tpu.core.serve import (ServeClient,
                                                StreamServer)
    from gelly_streaming_tpu.core.tenancy import TenantCohort

    eb, vb, num_w = 512, 1024, 6
    streams = {}
    for i in range(2):
        s, d = make_stream(num_w * eb, vb, seed=90 + i)
        streams["p%d" % i] = (s.astype(np.int32), d.astype(np.int32))

    # the 2-tenant vmapped batch is a NEW static shape in this
    # process, so the pump thread's first dispatch carries a cold
    # compile — the suite's 1s deadline (KNOBS) would kill the pump
    # thread before the injected fault ever fires. This leg's
    # contracts (overlap, kill recovery) don't exercise the deadline:
    # take the 30s guard its siblings use
    stage_prev = os.environ.get("GS_STAGE_TIMEOUT_S")
    os.environ["GS_STAGE_TIMEOUT_S"] = "30"

    # fault-free oracle: the direct sync cohort feed
    oracle = {}
    co = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
    for tid in streams:
        co.admit(tid)
    for w in range(num_w):
        for tid, (s, d) in sorted(streams.items()):
            co.feed(tid, s[w * eb:(w + 1) * eb],
                    d[w * eb:(w + 1) * eb])
        for tid, res in co.pump().items():
            oracle.setdefault(tid, []).extend(res)
    for tid in streams:
        oracle[tid].extend(co.close(tid))

    wal_dir = os.path.join(workdir, "pump_wal")
    ck_dir = os.path.join(workdir, "pump_ckpt")
    got = {tid: {} for tid in streams}
    cursors = {tid: 0 for tid in streams}

    def take(srv):
        for tid, rows in srv.results.items():
            for row in rows:
                got[tid][row["window"]] = row["summary"]

    def feed_one(cli, tid):
        s, d = streams[tid]
        c = cursors[tid]
        deadline = time.monotonic() + 60
        while True:
            r = cli.feed(tid, s[c:c + eb], d[c:c + eb])
            if r.get("ok"):
                cursors[tid] = c + eb
                return
            if r.get("error") != "TenantBackpressure" \
                    or time.monotonic() > deadline:
                raise SystemExit("chaos pump leg: feed refused: %r"
                                 % (r,))
            time.sleep(r.get("retry_after_s", 0.05))

    prev = os.environ.get("GS_PUMP")
    os.environ["GS_PUMP"] = "async"
    try:
        cohort = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        assert cohort.enable_wal(wal_dir)
        cohort.enable_auto_checkpoint(ck_dir, every_n_windows=2)
        server = StreamServer(cohort, port=0).start()
        if server._pump_thread is None or \
                not server._pump_thread.is_alive():
            raise SystemExit("chaos pump leg: GS_PUMP=async started "
                             "no pump thread")
        cli = ServeClient(server.port, timeout=60)
        for tid in sorted(streams):
            assert cli.admit(tid)["ok"]
        # window 0: plain async feeds, the pump delivers on its own
        for tid in sorted(streams):
            feed_one(cli, tid)
        # window 1: the overlap proof — hang one dispatch and land a
        # feed inside it (the ingest lock never waits on the pump)
        tids = sorted(streams)
        with faults.inject(faults.FaultSpec(
                site="tenant_prep", on_call=1, action="hang",
                seconds=0.5)):
            feed_one(cli, tids[0])
            time.sleep(0.1)  # let the pump pick the hang up
        for tid in tids[1:]:
            feed_one(cli, tid)
        overlap = int(server._stats.get("overlap_feeds", 0))
        # window 2: the kill — fatal fault INSIDE the pump thread
        with faults.inject(faults.FaultSpec(
                site="tenant_prep", on_call=1, fatal=True)) as plan:
            for tid in tids:
                feed_one(cli, tid)
            deadline = time.monotonic() + 30
            while not server.fatal \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            fired = list(plan.fired)
        if not server.fatal:
            raise SystemExit("chaos pump leg: the mid-pump kill "
                             "never fired (fired=%r)" % (fired,))
        take(server)
        try:
            cli.close()
        except OSError:
            pass
        server.close()  # the simulated process death

        # restart: fresh cohort + async server, checkpoint resume +
        # WAL suffix replay; re-feed only the un-acked suffix
        co2 = TenantCohort(edge_bucket=eb, vertex_bucket=vb)
        assert co2.enable_wal(wal_dir)
        co2.enable_auto_checkpoint(ck_dir, every_n_windows=2)
        rec = co2.recover()
        if not _ledger_has("wal_replayed"):
            raise SystemExit("chaos pump leg: no durable "
                             "wal_replayed event in the ledger")
        s2 = StreamServer(co2, port=0).start()
        cli2 = ServeClient(s2.port, timeout=60)
        live = True
        while live:
            live = False
            for tid in tids:
                if cursors[tid] >= num_w * eb:
                    continue
                feed_one(cli2, tid)
                live = True
        for tid in tids:
            assert cli2.close_tenant(tid)["ok"]
        cli2.close()
        s2.drain(deadline_s=60)
        take(s2)
        s2.close()
    finally:
        if prev is None:
            os.environ.pop("GS_PUMP", None)
        else:
            os.environ["GS_PUMP"] = prev
        if stage_prev is None:
            os.environ.pop("GS_STAGE_TIMEOUT_S", None)
        else:
            os.environ["GS_STAGE_TIMEOUT_S"] = stage_prev

    final = {tid: [got[tid][k] for k in sorted(got[tid])]
             for tid in streams}
    for tid in streams:
        if final[tid] != oracle[tid]:
            raise SystemExit(
                "chaos pump leg DIVERGED from the fault-free oracle "
                "for tenant %s (%d vs %d windows)"
                % (tid, len(final[tid]), len(oracle[tid])))
    if overlap < 1:
        raise SystemExit("chaos pump leg: the async pump never "
                         "overlapped ingest with dispatch "
                         "(overlap_feeds=0)")
    return {
        "parity": True,
        "overlap_feeds": overlap,
        "replayed_edges": rec["replayed_edges"],
        "faults_fired": [list(f) for f in fired],
        "digests": {tid: _summaries_digest(final[tid])
                    for tid in sorted(streams)},
    }


def leg_mesh(eb: int, vb: int, num_w: int, n_shards: int,
             workdir: str) -> dict:
    """The mesh drill: a sharded driver on the virtual CPU mesh takes
    a corrupt shard wire (caught by GS_MESH_WIRE_CHECK, retried clean)
    and then loses a shard for good (persistent shard_dispatch
    failure) — the sharded → single-chip-scan demotion ladder must
    re-enter from the last finalized chunk and the final digests must
    equal the fault-free single-chip oracle window by window. Then the
    cross-mesh-shape resume proof: a checkpoint taken on the n-way
    mesh resumes bit-exactly on 1 device (scan tier) AND on the numpy
    host tier."""
    from gelly_streaming_tpu.parallel.mesh import make_mesh
    from gelly_streaming_tpu.utils import checkpoint as ck

    def make(mesh=None, **kw):
        return StreamingAnalyticsDriver(
            window_ms=0, edge_bucket=eb, vertex_bucket=vb,
            analytics=("degrees", "cc", "bipartite", "triangles"),
            mesh=mesh, **kw)

    def digests(results):
        return [_digest(r) for r in results]

    mesh = make_mesh(n_shards)
    src, dst = make_stream(num_w * eb, vb // 2, seed=29)
    # the single-chip run IS the oracle; the fault-free mesh run must
    # already match it (the twin-parity contract)
    baseline = digests(make().run_arrays(src, dst))
    if digests(make(mesh=mesh).run_arrays(src, dst)) != baseline:
        raise SystemExit("chaos mesh leg: fault-free sharded run "
                         "diverged from the single-chip oracle")

    # the sharded scan first-compiles on the CPU mesh inside its
    # guarded dispatch: the drill needs a deadline that cuts a real
    # stall, not a compile (leg A owns the tight-deadline watchdog
    # proof)
    env_prev = {k: os.environ.get(k)
                for k in ("GS_STAGE_TIMEOUT_S", "GS_MESH_WIRE_CHECK")}
    os.environ["GS_STAGE_TIMEOUT_S"] = "30"
    os.environ["GS_MESH_WIRE_CHECK"] = "1"
    half = max(2, num_w // 2)
    try:
        demoted_before = len(resilience.demotion_events())
        drv = make(mesh=mesh)
        plan_specs = [
            faults.FaultSpec(site="shard_wire", on_call=1, times=1,
                             action="corrupt_shard", shard=1),
            faults.FaultSpec(site="shard_dispatch", on_call=2,
                             times=1 << 20, shard=2),  # THE DEAD SHARD
        ]
        with faults.inject(*plan_specs) as plan:
            got = digests(drv.run_arrays(src[:half * eb],
                                         dst[:half * eb]))
            got += digests(drv.run_arrays(src[half * eb:],
                                          dst[half * eb:]))
        if got != baseline:
            raise SystemExit(
                "chaos mesh leg DIVERGED from the fault-free run")
        fired = list(plan.fired)
        if not any(s == "shard_wire" for s, _n, _a in fired):
            raise SystemExit("chaos mesh leg: the corrupt wire never "
                             "fired (fired=%r)" % fired)
        demos = resilience.demotion_events()[demoted_before:]
        dead = [e for e in demos if e["from"] == "sharded"
                and e["shard_id"] == 2]
        if not dead:
            raise SystemExit("chaos mesh leg: the dead shard never "
                             "demoted the mesh (demotions=%r)" % demos)
        if dead[0]["mesh_shape"] != [n_shards]:
            raise SystemExit("chaos mesh leg: demotion lost its mesh "
                             "shape: %r" % dead[0])

        # ---- cross-mesh-shape resume: n-shard ckpt → 1 device + host
        ckpt = os.path.join(workdir, "mesh.npz")
        a = make(mesh=mesh)
        head = digests(a.run_arrays(src[:half * eb], dst[:half * eb]))
        ck.save(ckpt, a.state_dict())
        resumed_tiers = []
        for tier in ("scan", "host"):
            b = make(snapshot_tier=tier)  # mesh=None: 1 device / numpy
            if not b.try_resume(ckpt):
                raise SystemExit("chaos mesh leg: %s-tier resume found "
                                 "no checkpoint" % tier)
            off = b.edges_done
            tail = digests(b.run_arrays(src[off:], dst[off:]))
            if head + tail != baseline:
                raise SystemExit(
                    "chaos mesh leg: %s-tier resume of the %d-shard "
                    "checkpoint diverged" % (tier, n_shards))
            resumed_tiers.append(tier)
    finally:
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    return {
        "windows": num_w,
        "mesh_shape": [n_shards],
        "faults_fired": [list(f) for f in fired],
        "demotions": demos,
        "cross_mesh_resume_tiers": resumed_tiers,
        "parity": True,
    }


def leg_health(workdir: str) -> dict:
    """The health-plane drill: arm the metrics registry + /healthz
    endpoint (utils/metrics + utils/healthz) over a fused-scan stream
    that takes an h2d stall from the standard schedule, and assert

      (a) /healthz flips to `degraded` (HTTP 503) while the stall
          starves window finalizes past GS_HEALTH_STALE_S — within one
          watchdog interval (the watchdog ticks at stale/4),
      (b) it recovers to `ok` once the retried chunk finalizes,
      (c) the matching durable `health_degraded` / `health_recovered`
          events landed in the soak's run ledger, and
      (d) a fault-free run with the plane ARMED is bit-identical to
          the disarmed baseline (the GS_METRICS=0/1 parity contract
          at drill scale; the committed 524K/32768 proof lives in
          PERF_cpu.json's `metrics` section)."""
    import threading
    import time
    import urllib.error
    import urllib.request

    from gelly_streaming_tpu.utils import healthz, metrics

    eb, vb, num_w = 4096, 8192, 8
    src, dst = make_stream(num_w * eb, vb, seed=17)

    def make():
        eng = StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb)
        eng.MAX_WINDOWS = 2  # several chunks → finalizes spread out
        return eng

    baseline = make().process(src, dst)  # plane disarmed

    env_prev = {k: os.environ.get(k)
                for k in ("GS_METRICS", "GS_HEALTH_STALE_S",
                          "GS_AUTOTUNE")}
    os.environ["GS_METRICS"] = "1"
    os.environ["GS_HEALTH_STALE_S"] = "0.4"
    # static dispatch: an explored arm's compile pause mid-run would
    # be indistinguishable from the stall this leg is timing
    os.environ["GS_AUTOTUNE"] = "0"
    metrics.reset()
    srv = healthz.start(port=0)
    try:
        eng = make()
        armed = eng.process(src, dst)  # also warms every program
        if armed != baseline:
            raise SystemExit("health leg: ARMED fault-free run "
                             "diverged from the disarmed baseline")
        eng.reset()
        metrics.reset()  # clean transition log for the drill

        out, codes = [], []
        worker_err = []

        def run():
            # the standard h2d-stall class: hang 2.5s, cut by the
            # soak's 1s stage deadline, retried clean
            try:
                with faults.inject(faults.FaultSpec(
                        site="h2d", on_call=2, action="hang",
                        seconds=2.5)) as plan:
                    out.extend(eng.process(src, dst))
                if not any(s == "h2d" for s, _n, _a in plan.fired):
                    raise AssertionError(
                        "health leg: the h2d stall never fired")
            except BaseException as e:  # re-raised on the main thread
                worker_err.append(e)

        t = threading.Thread(target=run)
        t.start()
        url = "http://127.0.0.1:%d/healthz" % srv.port
        while t.is_alive():
            try:
                with urllib.request.urlopen(url, timeout=1) as resp:
                    codes.append(resp.status)
            except urllib.error.HTTPError as e:
                codes.append(e.code)
            time.sleep(0.05)
        t.join()
        if worker_err:
            raise worker_err[0]

        if out != baseline:
            raise SystemExit("health leg: the stalled+retried run "
                             "diverged from the fault-free baseline")
        if 503 not in codes:
            raise SystemExit("health leg: /healthz never reported "
                             "degraded during the h2d stall "
                             "(codes=%r)" % codes)
        trans = metrics.health_snapshot()["transitions"]
        kinds = [t0[0] for t0 in trans]
        if "degraded" not in kinds \
                or "ok" not in kinds[kinds.index("degraded"):]:
            raise SystemExit("health leg: no degraded→ok recovery in "
                             "the transition log: %r" % trans)
        # durable evidence in the soak ledger
        telemetry.flush()
        names = []
        path = telemetry.ledger_path()
        if path and os.path.exists(path):
            with open(path) as f:
                for line in f:
                    try:
                        names.append(json.loads(line).get("name"))
                    except ValueError:
                        pass
        for needed in ("health_degraded", "health_recovered"):
            if needed not in names:
                raise SystemExit("health leg: durable %r event "
                                 "missing from the run ledger"
                                 % needed)
        return {
            "windows": num_w,
            "healthz_port": srv.port,
            "probes": len(codes),
            "degraded_probes": codes.count(503),
            "transitions": trans,
            "armed_parity": True,
            "parity": True,
        }
    finally:
        healthz.stop()
        metrics.reset()
        for k, v in env_prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def assert_flight_recorder(num_kills: int) -> dict:
    """The flight-recorder durability leg: after the kill→resume
    drills, the run ledger (utils/telemetry, armed by main) must hold
    — under ONE trace ID — the chunk/stage spans recorded BEFORE the
    first simulated kill, the durable fatal/fault events themselves,
    and a post-kill `resume` event. This turns the recorder from
    instrumentation into verified crash evidence: a wedge that used
    to die as a dead queue hour now provably leaves its last spans on
    disk."""
    telemetry.flush()
    path = telemetry.ledger_path()
    if path is None or not os.path.exists(path):
        raise SystemExit("flight recorder: no ledger was written")
    trace = telemetry.trace_id()
    recs = []
    with open(path) as f:
        for line in f:
            try:
                recs.append(json.loads(line))
            except ValueError:
                pass
    body = [r for r in recs if r.get("t") != "meta"]
    foreign = [r for r in body if r.get("trace") != trace]
    if foreign:
        raise SystemExit("flight recorder: %d records carry a foreign "
                         "trace id" % len(foreign))
    fatals = [r for r in body if r.get("t") == "event"
              and r.get("name") == "fatal"]
    resumes = [r for r in body if r.get("t") == "event"
               and r.get("name") == "resume"]
    if len(fatals) < num_kills:
        raise SystemExit("flight recorder: expected >=%d fatal events,"
                         " ledger has %d" % (num_kills, len(fatals)))
    if not resumes:
        raise SystemExit("flight recorder: no resume event in the "
                         "ledger")
    kill_ts = min(float(r.get("ts", 0)) for r in fatals)
    pre_kill = [r for r in body if r.get("t") == "span"
                and float(r.get("ts", 0)) < kill_ts]
    if not pre_kill:
        raise SystemExit("flight recorder: no pre-kill spans survived "
                         "into the ledger")
    if not any(float(r.get("ts", 0)) > kill_ts for r in resumes):
        raise SystemExit("flight recorder: no resume event AFTER the "
                         "kill")
    return {
        "trace": trace,
        "ledger": os.path.basename(path),
        "records": len(body),
        "pre_kill_spans": len(pre_kill),
        "fatal_events": len(fatals),
        "resume_events": len(resumes),
        "durable_parity": True,
    }


def run_gslint() -> dict:
    """One gslint pass over the package (tools/gslint), returning the
    schema-validated JSON report. Used twice by main(): before and
    after the soak — the linter reads only committed source, so the
    soak's generated artifacts (tuning caches, ledgers, checkpoints,
    demotion logs) must not change a single finding."""
    from tools.gslint import report_json, run_lint, validate_report

    report = report_json(run_lint(["gelly_streaming_tpu"]),
                         ["gelly_streaming_tpu"])
    problems = validate_report(report)
    assert problems == [], problems
    return report


def assert_gslint_hermetic(before: dict, after: dict) -> dict:
    """The gslint-hermetic leg: a clean tree stays clean through the
    whole chaos soak (no rule may depend on runtime state), and the
    verdict is bit-identical finding-for-finding."""
    assert after["findings"] == before["findings"], (
        "gslint verdict changed across the soak — a rule is reading "
        "runtime state")
    assert after["counts"]["new"] == 0, (
        "tree not gslint-clean: %d new finding(s)"
        % after["counts"]["new"])
    return {"findings": after["counts"]["total"],
            "baselined": after["counts"]["baselined"],
            "new": after["counts"]["new"],
            "hermetic": True}


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--edges", type=int, default=524288)
    ap.add_argument("--eb", type=int, default=32768)
    ap.add_argument("--vertices", type=int, default=65536)
    ap.add_argument("--engine-windows", type=int, default=8,
                    help="windows of the stream leg B replays "
                    "(the fused scan's CPU cost bounds the soak)")
    ap.add_argument("--engine-eb", type=int, default=4096,
                    help="leg B edge bucket: the fused scan's CPU "
                    "materialize of a 32768-wide chunk legitimately "
                    "exceeds the 1 s chaos deadline — the row-scale "
                    "parity proof lives in leg A; leg B contributes "
                    "the h2d/kill fault classes at a bucket the "
                    "deadline fits")
    ap.add_argument("--mesh-devices", type=int, default=4,
                    help="virtual CPU devices for the mesh drill "
                    "(pins a CPU backend with that many devices "
                    "before jax initializes; 0 skips the mesh leg "
                    "and leaves the backend untouched)")
    ap.add_argument("--mesh-eb", type=int, default=2048,
                    help="mesh-leg edge bucket (the sharded CPU scan "
                    "bounds the soak; the row-scale parity proof is "
                    "leg A's)")
    ap.add_argument("--mesh-windows", type=int, default=8)
    ap.add_argument("--out", default=None,
                    help="write the JSON summary here")
    args = ap.parse_args()

    if args.mesh_devices:
        # must precede the first jax computation in this process
        from gelly_streaming_tpu.core.platform import cpu_mesh

        cpu_mesh(args.mesh_devices)

    for k, v in KNOBS.items():
        os.environ.setdefault(k, v)
    resilience.reset_demotions()

    lint_before = run_gslint()
    src, dst = make_stream(args.edges, args.vertices)
    num_w = -(-args.edges // args.eb)
    with tempfile.TemporaryDirectory(prefix="gs-chaos-") as workdir:
        path = os.path.join(workdir, "edges.txt")
        _write_stream(path, src, dst)
        # arm the flight recorder for the whole soak: every leg's
        # spans, faults, demotions, checkpoints and resumes land in
        # ONE run ledger under one trace ID, and the recorder leg
        # below asserts the ledger survived the kills
        tel_prev = {k: os.environ.get(k)
                    for k in ("GS_TELEMETRY", "GS_TRACE_DIR")}
        os.environ["GS_TELEMETRY"] = "1"
        os.environ["GS_TRACE_DIR"] = workdir
        telemetry.reset()
        try:
            a = leg_driver(path, args.eb, num_w, workdir)
            # autotune leg: scan tier + live tuner, kill → resume,
            # tuning state must round-trip the checkpoint bit-for-bit
            at = leg_autotune(path, args.eb, num_w, workdir)
            # resident leg: the donated megakernel killed
            # mid-superbatch → resume → sha256 window parity with the
            # fault-free SCAN-tier oracle
            rs = leg_resident(path, args.eb, num_w, workdir)
            # leg B runs a right-sized twin stream: the fused scan's
            # CPU cold-compile + materialize must FIT the 1 s chaos
            # deadline (at vb=65536 the first chunk's finalize
            # legitimately exceeds it); the row-scale parity proof is
            # leg A's
            engine_vb = 8192
            b_src, b_dst = make_stream(
                args.engine_windows * args.engine_eb, engine_vb,
                seed=13)
            b = leg_engine(b_src, b_dst, args.engine_eb, engine_vb,
                           args.engine_windows, workdir)
            # GNN leg: the journal-armed windowed-GNN engine killed
            # fatally mid-stream → checkpoint + WAL replay → summary
            # stream AND feature slab ≡ the fault-free oracle
            gn = leg_gnn(workdir)
            # health-plane leg: /healthz flips degraded on a stalled
            # h2d, recovers after the retry, durable events + armed
            # digest parity
            h = leg_health(workdir)
            # tenancy leg: one tenant's prep poisoned mid-cohort →
            # isolated demotion; fatal kill mid-dispatch → per-tenant
            # checkpoint resume; per-tenant digests equal the
            # fault-free sequential oracle
            tn = leg_tenancy(workdir)
            # provenance leg: the fully armed cohort killed fatally
            # mid-dispatch -> WAL/checkpoint recovery -> the
            # re-emitted provenance records byte-identical to the
            # fault-free oracle's ledger (the audit trail cannot
            # fork across a crash)
            pv = leg_provenance(workdir)
            # serve leg: the durable front-end — loopback kill →
            # WAL-replay parity, torn journal tail falls back one
            # record, slow client shed, SIGTERM drain exits 0 with a
            # sealed journal (subprocess)
            sv = leg_serve(workdir)
            # latency leg: kill→WAL-replay recovery must preserve
            # admission timestamps — replayed windows report honest,
            # larger latency (never reset-to-zero) and their stage
            # waterfalls still reconcile
            ly = leg_latency(workdir)
            # poison leg: one hostile tenant floods garbage — the
            # sanitizer rejects to the DLQ (every record recoverable),
            # the bulkhead bisects the poisoned dispatch and
            # quarantines exactly the hostile stream, the 7 healthy
            # tenants stay bit-identical, and a serve subprocess
            # drains rc=0 under the same flood
            po = leg_poison(workdir)
            # pump leg: GS_PUMP=async — real ingest/dispatch overlap,
            # then a fatal kill INSIDE the pump thread → WAL-replay
            # recovery into a fresh async server, per-tenant digests
            # equal the sync fault-free oracle
            pp = leg_pump(workdir)
            # mesh leg: corrupt wire → retry, dead shard → demotion →
            # parity, n-shard checkpoint → 1-device + host-twin resume
            m = (leg_mesh(args.mesh_eb, 4096, args.mesh_windows,
                          args.mesh_devices, workdir)
                 if args.mesh_devices else None)
            # flight-recorder leg: nine kills fired above (driver,
            # autotune, resident, engine, gnn, tenancy, provenance,
            # serve, pump) — the ledger must prove all
            fr = assert_flight_recorder(num_kills=9)
            fr["span_summary"] = telemetry.summary(top=12)
        finally:
            telemetry.reset()  # close the ledger inside the tempdir
            for k, v in tel_prev.items():  # restore, never just pop:
                if v is None:              # an operator-armed session
                    os.environ.pop(k, None)  # must stay armed after
                else:
                    os.environ[k] = v

    classes = set()
    for leg in (a, b):
        for site, _n, action in leg["faults_fired"]:
            if action == "hang":
                classes.add("h2d_timeout_retry")
            elif site == "prep":
                classes.add("prep_failure")
            elif action == "raise":
                classes.add("kill_resume")
    required = {"prep_failure", "h2d_timeout_retry", "kill_resume"}
    for site, _n, action in rs["faults_fired"]:
        if site == "dispatch" and action == "raise":
            classes.add("resident_kill_resume")
    required.add("resident_kill_resume")
    for site, _n, action in gn["faults_fired"]:
        if site == "dispatch" and action == "raise":
            classes.add("gnn_kill_replay")
    required.add("gnn_kill_replay")
    for site, _n, action in tn["faults_fired"]:
        if site == "tenant_prep" and action == "raise":
            classes.add("tenant_demotion")
        elif site == "cohort_dispatch" and action == "raise":
            classes.add("tenant_kill_resume")
    required |= {"tenant_demotion", "tenant_kill_resume"}
    for site, _n, action in pv["faults_fired"]:
        if site == "cohort_dispatch" and action == "raise" \
                and pv["re_emitted"] > 0:
            classes.add("provenance_replay_identity")
    required.add("provenance_replay_identity")
    for site, _n, action in sv["kill"]["faults_fired"]:
        if site == "cohort_dispatch" and action == "raise":
            classes.add("serve_kill_replay")
    if sv["torn_tail"]["parity"]:
        classes.add("serve_torn_tail")
    if sv["slow_client"]["shed"]:
        classes.add("serve_slow_client_shed")
    if sv["drain"]["rc"] == 0 and sv["drain"]["sealed"]:
        classes.add("serve_sigterm_drain")
    if ly["preserved"]:
        classes.add("latency_replay_stamps")
    for site, _n, action in po["faults_fired"]:
        if site == "cohort_dispatch" and action == "call":
            classes.add("poison_isolation")
    if po["dlq_recovered"]:
        classes.add("dlq_recovery")
    for site, _n, action in pp["faults_fired"]:
        if site == "tenant_prep" and action == "raise":
            classes.add("pump_kill_replay")
    if pp["overlap_feeds"] >= 1:
        classes.add("pump_overlap")
    required |= {"serve_kill_replay", "serve_torn_tail",
                 "serve_slow_client_shed", "serve_sigterm_drain",
                 "latency_replay_stamps", "poison_isolation",
                 "dlq_recovery", "pump_kill_replay", "pump_overlap"}
    if m is not None:
        for site, _n, action in m["faults_fired"]:
            if action == "corrupt_shard":
                classes.add("shard_wire_corrupt_retry")
            elif site == "shard_dispatch" and action == "raise":
                classes.add("dead_shard_demotion")
        if m["cross_mesh_resume_tiers"] == ["scan", "host"]:
            classes.add("cross_mesh_resume")
        required |= {"shard_wire_corrupt_retry", "dead_shard_demotion",
                     "cross_mesh_resume"}
    missing = required - classes
    if missing:
        raise SystemExit("chaos schedule incomplete: %s never fired"
                         % sorted(missing))

    # gslint-hermetic leg: the invariant checker's verdict must be
    # bit-identical after the soak's generated artifacts
    gl = assert_gslint_hermetic(lint_before, run_gslint())

    summary = {
        "edges": args.edges, "edge_bucket": args.eb,
        "vertices": args.vertices,
        # effective values: KNOBS applies via setdefault, so an env
        # override (e.g. a slower machine widening the stage deadline)
        # must show up in the committed artifact
        "knobs": {k: os.environ.get(k, v) for k, v in KNOBS.items()},
        "driver_leg": a, "engine_leg": b, "autotune_leg": at,
        "resident_leg": rs,
        "gnn_leg": gn,
        "health_leg": h,
        "tenancy_leg": tn,
        "provenance_leg": pv,
        "serve_leg": sv,
        "latency_leg": ly,
        "poison_leg": po,
        "pump_leg": pp,
        "mesh_leg": m,
        "flight_recorder_leg": fr,
        "gslint_leg": gl,
        "fault_classes_fired": sorted(classes),
        "demotions": resilience.demotion_events(),
        "parity": True,
    }
    print(json.dumps(summary, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2)
        print("wrote %s" % args.out, file=sys.stderr)


if __name__ == "__main__":
    main()

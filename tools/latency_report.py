#!/usr/bin/env python
"""Per-window ingest→deliver waterfall renderer + reconciliation
check over the flight-recorder ledger's `latency.window` events.

The latency plane (utils/latency.py, GS_LATENCY=1) records one event
per finalized window: its end-to-end ingest→deliver seconds and the
stage decomposition (admission / queue_wait / prep / h2d / dispatch /
finalize / deliver) derived from consecutive boundary stamps. Because
stages are consecutive diffs of ONE clock, they must sum to the
end-to-end within tolerance — the same conservation discipline
tools/explain_perf.py holds for cost attribution. This tool:

  - renders one window's life across the stages as an ASCII waterfall
    (`--tenant T --window N`, or the worst-e2e window by default);
  - rolls windows up per tenant (`--tenant` filters): count, e2e
    p50/p95/p99 (`--percentile` picks one), per-stage share;
  - RECONCILES every window: |sum(stages) − e2e| must stay within
    `--tolerance` (default 5%) of the end-to-end (with a small
    absolute floor for µs-scale windows), and no stage may be
    negative. Any violation → non-zero exit, so CI (gate 8,
    tools/latency_smoke.py) catches a decomposition that silently
    stops covering the end-to-end it claims to explain.

Usage:
  python tools/latency_report.py LEDGER.jsonl [--tenant T]
         [--window N] [--percentile 99] [--tolerance 0.05] [--json]

The ledger needs GS_TELEMETRY=1 + GS_TRACE_DIR (flushed); a run armed
with only GS_LATENCY=1 serves /healthz and /metrics but writes no
ledger rows for this tool.

Exit status: 0 clean, 1 reconciliation violation, 2 usage/no data.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# canonical render order (utils/latency.STAGES without importing the
# package: this tool must run ledger-only, no jax import)
STAGES = ("admission", "queue_wait", "prep", "h2d", "dispatch",
          "finalize", "deliver")
# reconciliation floor for µs-scale windows — inlined twin of
# utils/latency.RECONCILE_FLOOR_S / reconcile(); keep in lockstep
ABS_FLOOR_S = 50e-6


def load_windows(path: str) -> list:
    """The `latency.window` records of one ledger (torn final line
    tolerated — the telemetry reader discipline)."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail
            if rec.get("t") == "event" \
                    and rec.get("name") == "latency.window":
                a = rec.get("a") or {}
                if isinstance(a.get("stages"), dict) \
                        and isinstance(a.get("e2e_s"), (int, float)):
                    rows.append(a)
    return rows


def reconcile(win: dict, tolerance: float):
    """(ok, problem_or_None) for one window record: stages must be
    non-negative and sum to e2e within tolerance."""
    stages = win["stages"]
    e2e = float(win["e2e_s"])
    for name, dur in stages.items():
        if not isinstance(dur, (int, float)) or dur < 0:
            return False, "stage %r is negative/non-numeric (%r)" % (
                name, dur)
    total = sum(float(v) for v in stages.values())
    slack = max(tolerance * e2e, ABS_FLOOR_S)
    if abs(total - e2e) > slack:
        return False, (
            "unaccounted time: stages sum to %.6fs but end-to-end is "
            "%.6fs (|Δ|=%.6fs > %.6fs allowed)"
            % (total, e2e, abs(total - e2e), slack))
    return True, None


def percentile(samples, p: int) -> float:
    """Nearest-rank percentile (the telemetry definition, inlined so
    the tool stays import-light)."""
    xs = sorted(samples)
    if not xs:
        return 0.0
    rank = max(1, -(-p * len(xs) // 100))
    return float(xs[min(rank, len(xs)) - 1])


def rollup(wins: list, p: int) -> dict:
    """Per-tenant rows: window count, replayed count, e2e pXX, and
    per-stage mean share of the end-to-end."""
    by_tenant = {}
    for w in wins:
        by_tenant.setdefault(str(w.get("tenant", "?")), []).append(w)
    out = {}
    for tid, rows in sorted(by_tenant.items()):
        e2es = [float(w["e2e_s"]) for w in rows]
        total_e2e = sum(e2es) or 1.0
        stage_totals = {}
        for w in rows:
            for name, dur in w["stages"].items():
                stage_totals[name] = stage_totals.get(name, 0.0) \
                    + float(dur)
        out[tid] = {
            "windows": len(rows),
            "replayed": sum(1 for w in rows if w.get("replayed")),
            "e2e_p%d_s" % p: round(percentile(e2es, p), 6),
            "e2e_p50_s": round(percentile(e2es, 50), 6),
            "e2e_max_s": round(max(e2es), 6),
            "stages": {
                name: {"total_s": round(tot, 6),
                       "share": round(tot / total_e2e, 4)}
                for name, tot in sorted(
                    stage_totals.items(),
                    key=lambda kv: STAGES.index(kv[0])
                    if kv[0] in STAGES else 99)},
        }
    return out


def render_waterfall(win: dict, width: int = 44) -> str:
    """One window's life across the stages as an ASCII waterfall."""
    e2e = float(win["e2e_s"])
    lines = [
        "window %s (tenant %s, %s edges%s)  end-to-end %.3f ms"
        % (win.get("window", "?"), win.get("tenant", "?"),
           win.get("edges", "?"),
           ", replayed" if win.get("replayed") else "", e2e * 1e3)]
    at = 0.0
    scale = width / e2e if e2e > 0 else 0.0
    for name in STAGES:
        dur = win["stages"].get(name)
        if dur is None:
            continue
        dur = float(dur)
        lo = int(at * scale)
        ln = max(1, int(dur * scale)) if dur > 0 else 0
        bar = " " * lo + "#" * min(ln, width - lo)
        lines.append("  %-10s %9.3f ms  %4.1f%%  |%-*s|"
                     % (name, dur * 1e3,
                        100.0 * dur / e2e if e2e else 0.0,
                        width, bar))
        at += dur
    un = e2e - sum(float(v) for v in win["stages"].values())
    lines.append("  %-10s %9.3f ms  %4.1f%%"
                 % ("unaccounted", un * 1e3,
                    100.0 * un / e2e if e2e else 0.0))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("ledger", help="run ledger (trace_*.jsonl) of a "
                                   "GS_LATENCY=1 + GS_TELEMETRY=1 run")
    ap.add_argument("--tenant", default=None,
                    help="restrict to one tenant's windows")
    ap.add_argument("--window", type=int, default=None,
                    help="render this window ordinal's waterfall "
                         "(default: the worst end-to-end)")
    ap.add_argument("--percentile", type=int, default=99,
                    choices=(50, 90, 95, 99),
                    help="roll-up percentile (default 99)")
    ap.add_argument("--tolerance", type=float, default=0.05,
                    help="allowed |sum(stages) − e2e| as a fraction "
                         "of e2e (default 0.05)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    args = ap.parse_args(argv)
    if not 0 < args.tolerance < 1:
        print("latency_report: --tolerance must be in (0, 1)",
              file=sys.stderr)
        return 2

    try:
        wins = load_windows(args.ledger)
    except OSError as e:
        print("latency_report: %s" % e, file=sys.stderr)
        return 2
    if args.tenant is not None:
        wins = [w for w in wins
                if str(w.get("tenant")) == args.tenant]
    if args.window is not None:
        sel = [w for w in wins if w.get("window") == args.window]
        if args.tenant is None and len(
                {str(w.get("tenant")) for w in sel}) > 1:
            print("latency_report: --window %d matches several "
                  "tenants — add --tenant" % args.window,
                  file=sys.stderr)
            return 2
    if not wins:
        print("latency_report: no latency.window records in %s — arm "
              "GS_LATENCY=1 AND GS_TELEMETRY=1 (+GS_TRACE_DIR) and "
              "flush the ring" % args.ledger, file=sys.stderr)
        return 2

    violations = []
    for w in wins:
        ok, problem = reconcile(w, args.tolerance)
        if not ok:
            violations.append(
                {"tenant": str(w.get("tenant")),
                 "window": w.get("window"), "problem": problem})

    roll = rollup(wins, args.percentile)
    if args.window is not None:
        focus = next((w for w in wins
                      if w.get("window") == args.window), None)
        if focus is None:
            print("latency_report: window %d not found"
                  % args.window, file=sys.stderr)
            return 2
    else:
        focus = max(wins, key=lambda w: float(w["e2e_s"]))

    if args.json:
        print(json.dumps({
            "ledger": args.ledger,
            "windows": len(wins),
            "tolerance": args.tolerance,
            "rollup": roll,
            "waterfall": focus,
            "violations": violations,
        }, indent=2))
    else:
        print(render_waterfall(focus))
        print()
        print("per-tenant roll-up (%d windows, p%d):"
              % (len(wins), args.percentile))
        for tid, row in roll.items():
            print("  %-12s %4d windows (%d replayed)  "
                  "p50 %.3f ms  p%d %.3f ms  max %.3f ms"
                  % (tid, row["windows"], row["replayed"],
                     row["e2e_p50_s"] * 1e3, args.percentile,
                     row["e2e_p%d_s" % args.percentile] * 1e3,
                     row["e2e_max_s"] * 1e3))
            for name, srow in row["stages"].items():
                print("      %-10s %9.3f ms total  %5.1f%%"
                      % (name, srow["total_s"] * 1e3,
                         100.0 * srow["share"]))
    if violations:
        for v in violations:
            print("RECONCILIATION FAILED tenant=%s window=%s: %s"
                  % (v["tenant"], v["window"], v["problem"]),
                  file=sys.stderr)
        return 1
    print("reconciliation ok: %d window(s), stages sum to "
          "end-to-end within %.0f%%"
          % (len(wins), 100 * args.tolerance),
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())

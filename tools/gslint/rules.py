"""The six project invariants, as AST rules over committed source.

Each rule is deliberately PROJECT-SPECIFIC: the module sets and call
surfaces below encode this repo's architecture (DESIGN.md §13), not a
general Python style. False positives are handled by the pragma /
baseline machinery in tools/gslint/__init__.py, so rules here lean
toward catching the failure shape over statistical precision.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import re
from typing import Dict, List, Optional, Sequence, Set

from . import Finding, ModuleCtx, Rule

PKG = "gelly_streaming_tpu"


def _dotted(node) -> str:
    """'a.b.c' for nested Attribute/Name chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _imports_jax(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] == "jax" for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").split(".")[0] == "jax":
                return True
    return False


# ======================================================================
# R1 — host-sync discipline
# ======================================================================
class HostSyncRule(Rule):
    """Host↔device synchronization is the dispatch wall (BENCH_r05:
    the round-trip, not compute, bounds the device path). Every d2h
    materialization must happen at a sanctioned egress/finalize/
    mirror-sync site — the driver's delivery boundary, the delta
    egress decode, the host-twin mirror sync — where it is batched,
    telemetry-covered, and demotion-aware. A stray `np.asarray(...)`
    on a device value anywhere else inserts an unaccounted sync point
    that the megakernel/Pallas refactors will silently inherit.

    Scope: modules that import jax (elsewhere `np.asarray` is
    numpy-on-numpy, not a sync), minus the sanctioned modules."""

    rule_id = "R1"
    name = "host-sync"
    doc = ("d2h sync surface calls outside the sanctioned "
           "egress/finalize/mirror-sync modules")

    SANCTIONED = (
        PKG + "/core/driver.py",       # delivery/finalize boundary
        PKG + "/ops/delta_egress.py",  # the egress wire itself
        PKG + "/parallel/host_twin.py",  # mirror sync / demotion
    )
    # attribute-call surface: full dotted suffixes
    SYNC_CALLS = {
        "np.asarray", "np.array", "numpy.asarray", "numpy.array",
        "jax.device_get", "device_get",
    }
    SYNC_METHODS = {"item", "block_until_ready"}

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        if not ctx.path.startswith(PKG + "/"):
            return []
        if ctx.path in self.SANCTIONED:
            return []
        if not _imports_jax(ctx.tree):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            hit = None
            if dotted in self.SYNC_CALLS:
                hit = dotted
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in self.SYNC_METHODS
                  and not node.args and not node.keywords):
                hit = ".%s()" % node.func.attr
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in ("float", "int")
                  and len(node.args) == 1
                  and isinstance(node.args[0],
                                 (ast.Subscript, ast.Call))):
                # float(x[w]) / int(dev()) — the forced-scalar shape;
                # plain float(name) is everyday host arithmetic
                hit = "%s(<device expr>)" % node.func.id
            if hit:
                out.append(self.finding(
                    ctx, node,
                    "host-sync surface call %s outside a sanctioned "
                    "egress site — batch it through the driver "
                    "delivery boundary, ops/delta_egress, or the "
                    "parallel/host_twin mirror sync" % hit))
        return out


# ======================================================================
# R2 — jit purity
# ======================================================================
class JitPurityRule(Rule):
    """Anything reachable from a jit/scan/shard_map root executes at
    TRACE time: an `os.environ` read there silently freezes the
    knob's value into the compiled program (flipping it mid-process —
    which tests and tools/chaos_run.py do — then changes nothing), a
    clock or telemetry call records trace time once instead of run
    time, and a module-level mutable read bakes in whatever the first
    trace saw. Roots: @jit decorators, jit()/lax.scan/lax.map/
    while_loop/fori_loop/cond/shard_map call sites; reachability is
    name-resolved within the module (conservative but deterministic).
    """

    rule_id = "R2"
    name = "jit-purity"
    doc = ("impure reads (env/clock/telemetry/module mutables) "
           "reachable from traced code")

    _JIT_WRAP = {"jit", "jax.jit"}
    # callable-argument positions of the traced-control-flow surface
    _TRACED_ARGS = {
        "lax.scan": (0,), "jax.lax.scan": (0,),
        "lax.map": (0,), "jax.lax.map": (0,),
        "lax.while_loop": (0, 1), "jax.lax.while_loop": (0, 1),
        "lax.fori_loop": (2,), "jax.lax.fori_loop": (2,),
        "lax.cond": (1, 2), "jax.lax.cond": (1, 2),
        "shard_map": (0,), "shard_map_norep": (0,),
        "jax.experimental.shard_map.shard_map": (0,),
        # Pallas kernel bodies trace like any jit root (and freeze
        # even harder: the kernel compiles once per shape into a
        # Mosaic binary) — ops/pallas_window.py and the seed kernels
        # are in-scope via their pallas_call sites
        "pl.pallas_call": (0,), "pallas_call": (0,),
        "pallas.pallas_call": (0,),
    }
    _CLOCK_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
                    "time.sleep", "time.process_time"}

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        if not ctx.path.startswith(PKG + "/"):
            return []
        defs: Dict[str, ast.AST] = {}
        mutables: Set[str] = set()
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        defs.setdefault(sub.name, sub)
            elif isinstance(node, ast.Assign):
                if self._is_mutable_literal(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            mutables.add(t.id)
                if isinstance(node.value, ast.Lambda):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            defs[t.id] = node.value
        roots = self._roots(ctx, defs)
        reached: List[ast.AST] = []
        seen: Set[int] = set()
        queue = list(roots)
        while queue:
            fn = queue.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            reached.append(fn)
            for callee in self._local_callees(fn, defs):
                queue.append(callee)
        out: List[Finding] = []
        flagged: Set[int] = set()
        for fn in reached:
            for f in self._impure(ctx, fn, mutables):
                marker = (f.line, f.col, f.message)
                if marker not in flagged:
                    flagged.add(marker)
                    out.append(f)
        return out

    @staticmethod
    def _is_mutable_literal(value) -> bool:
        if isinstance(value, (ast.List, ast.Dict, ast.Set,
                              ast.ListComp, ast.DictComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            return _dotted(value.func) in (
                "dict", "list", "set", "collections.deque",
                "collections.defaultdict", "collections.OrderedDict")
        return False

    def _roots(self, ctx: ModuleCtx,
               defs: Dict[str, ast.AST]) -> List[ast.AST]:
        roots: List[ast.AST] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    d = dec.func if isinstance(dec, ast.Call) else dec
                    if _dotted(d) in self._JIT_WRAP:
                        roots.append(node)
                    elif (isinstance(dec, ast.Call)
                          and _dotted(dec.func).endswith("partial")
                          and dec.args
                          and _dotted(dec.args[0]) in self._JIT_WRAP):
                        roots.append(node)
            elif isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                arg_idx = ()
                if dotted in self._JIT_WRAP:
                    arg_idx = (0,)
                elif dotted in self._TRACED_ARGS:
                    arg_idx = self._TRACED_ARGS[dotted]
                for i in arg_idx:
                    if i < len(node.args):
                        roots.extend(self._resolve(node.args[i], defs))
        return roots

    @staticmethod
    def _resolve(arg, defs: Dict[str, ast.AST]) -> List[ast.AST]:
        if isinstance(arg, ast.Lambda):
            return [arg]
        if isinstance(arg, ast.Name) and arg.id in defs:
            return [defs[arg.id]]
        if isinstance(arg, ast.Attribute) and arg.attr in defs:
            return [defs[arg.attr]]  # self._meth → any same-named def
        if isinstance(arg, ast.Call):
            # partial(f, ...) / jit(f) nests
            inner = [a for a in arg.args]
            out = []
            for a in inner:
                out.extend(JitPurityRule._resolve(a, defs))
            return out
        return []

    @staticmethod
    def _local_callees(fn, defs: Dict[str, ast.AST]) -> List[ast.AST]:
        out = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                if isinstance(node.func, ast.Name) \
                        and node.func.id in defs:
                    out.append(defs[node.func.id])
                elif (isinstance(node.func, ast.Attribute)
                      and isinstance(node.func.value, ast.Name)
                      and node.func.value.id == "self"
                      and node.func.attr in defs):
                    out.append(defs[node.func.attr])
        return out

    def _impure(self, ctx: ModuleCtx, fn,
                mutables: Set[str]) -> List[Finding]:
        out: List[Finding] = []
        local_shadow = {a.arg for a in getattr(fn, "args",
                                               ast.arguments(
                                                   posonlyargs=[],
                                                   args=[], kwonlyargs=[],
                                                   kw_defaults=[],
                                                   defaults=[])).args}
        for node in ast.walk(fn):
            dotted = _dotted(node) if isinstance(node,
                                                 ast.Attribute) else ""
            if dotted == "os.environ":
                out.append(self.finding(
                    ctx, node,
                    "os.environ read reachable from traced code — the "
                    "value freezes at compile time; hoist the "
                    "utils/knobs read out of the traced function"))
            elif isinstance(node, ast.Call):
                cd = _dotted(node.func)
                if cd == "os.getenv":
                    out.append(self.finding(
                        ctx, node,
                        "environment read reachable from traced code "
                        "— freezes at compile time"))
                elif cd in self._CLOCK_CALLS:
                    out.append(self.finding(
                        ctx, node,
                        "%s inside traced code measures trace time "
                        "once, not run time — time outside the jitted "
                        "program" % cd))
                elif cd.startswith("telemetry."):
                    out.append(self.finding(
                        ctx, node,
                        "telemetry call inside traced code records at "
                        "trace time only — record around the "
                        "dispatch, not inside it"))
                elif cd.startswith("metrics."):
                    out.append(self.finding(
                        ctx, node,
                        "metrics-registry call inside traced code "
                        "records at trace time only (and its knob "
                        "gate freezes) — mark around the dispatch, "
                        "not inside it"))
                elif cd.startswith("costmodel."):
                    out.append(self.finding(
                        ctx, node,
                        "cost-observatory call inside traced code "
                        "captures/tags at trace time only (and its "
                        "knob gate freezes) — wrap the dispatch "
                        "entry point, never the traced body"))
                elif cd.startswith("knobs."):
                    out.append(self.finding(
                        ctx, node,
                        "knob read inside traced code freezes the "
                        "env value at compile time — hoist the "
                        "%s call out of the traced function" % cd))
            elif (isinstance(node, ast.Name)
                  and isinstance(node.ctx, ast.Load)
                  and node.id in mutables
                  and node.id not in local_shadow):
                out.append(self.finding(
                    ctx, node,
                    "module-level mutable `%s` read inside traced "
                    "code — its trace-time contents are baked into "
                    "the compiled program" % node.id))
        return out


# ======================================================================
# R3 — knob registry
# ======================================================================
class KnobRegistryRule(Rule):
    """Every `GS_*` knob goes through utils/knobs.py: one typed
    declaration, live reads, KnobError on malformed values, and a
    README table rendered FROM the registry. Flags (a) any
    os.environ/os.getenv use in the package outside utils/knobs.py
    and the non-knob backend setup in core/platform.py, (b) `GS_*`
    string literals that aren't registered knobs (typo'd names read
    as silent defaults), (c) README knob-table drift from
    knobs.render_table()."""

    rule_id = "R3"
    name = "knob-registry"
    doc = ("GS_* env reads outside utils/knobs.py; unregistered GS_* "
           "literals; README knob-table drift")

    ALLOWED = (PKG + "/utils/knobs.py", PKG + "/core/platform.py")
    _GS_RE = re.compile(r"^GS_[A-Z0-9_]+$")

    @staticmethod
    def registry():
        """The live knob registry, loaded standalone by file path —
        importing the package itself would pull in jax and make the
        linter's verdict depend on the runtime environment. Cached in
        sys.modules (dataclasses resolves type hints through it)."""
        import sys

        if "_gs_knobs" in sys.modules:
            return sys.modules["_gs_knobs"]
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            PKG, "utils", "knobs.py")
        spec = importlib.util.spec_from_file_location("_gs_knobs", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["_gs_knobs"] = mod
        spec.loader.exec_module(mod)
        return mod

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        if not ctx.path.startswith(PKG + "/"):
            return []
        out: List[Finding] = []
        if ctx.path not in self.ALLOWED:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Attribute) \
                        and _dotted(node) == "os.environ":
                    out.append(self.finding(
                        ctx, node,
                        "os.environ access outside utils/knobs.py — "
                        "declare the knob in the registry and read it "
                        "with knobs.get_*"))
                elif isinstance(node, ast.Call) \
                        and _dotted(node.func) == "os.getenv":
                    out.append(self.finding(
                        ctx, node,
                        "os.getenv outside utils/knobs.py — declare "
                        "the knob in the registry"))
        known = set(self.registry().REGISTRY)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str) \
                    and self._GS_RE.match(node.value) \
                    and node.value not in known:
                out.append(self.finding(
                    ctx, node,
                    "unregistered GS_* name %r — a typo'd knob reads "
                    "as its silent default; register it in "
                    "utils/knobs.py" % node.value))
        return out

    def check_project(self, ctxs: Sequence[ModuleCtx],
                      repo: str) -> List[Finding]:
        """README knob table == knobs.render_table(), row for row."""
        readme = os.path.join(repo, "README.md")
        if not os.path.exists(readme):
            return []
        with open(readme, encoding="utf-8") as f:
            text = f.read()
        knobs = self.registry()
        want = knobs.render_table()
        if want in text:
            return []
        want_rows = {line.split("|")[1].strip(): line
                     for line in want.splitlines()[2:]}
        have_rows = {}
        for line in text.splitlines():
            m = re.match(r"\|\s*(`GS_[A-Z0-9_]+`)\s*\|", line)
            if m:
                have_rows[m.group(1)] = line.strip()
        problems = []
        for name, row in want_rows.items():
            if name not in have_rows:
                problems.append("missing row %s" % name)
            elif have_rows[name] != row:
                problems.append("stale row %s" % name)
        for name in have_rows:
            if name not in want_rows:
                problems.append("unregistered row %s" % name)
        if not problems:
            problems = ["table block differs from render_table() "
                        "(row order or header)"]
        # stale/unregistered rows are the actionable ones; missing
        # rows are usually a wholesale-regeneration symptom — keep
        # the former ahead of the truncation cap
        problems.sort(key=lambda p: (p.startswith("missing"), p))
        return [Finding(
            rule=self.rule_id, name=self.name, path="README.md",
            line=1, col=0,
            message="README GS_* knob table drifted from the "
                    "utils/knobs registry: %s — regenerate with "
                    "`python -m tools.gslint --knob-table`"
                    % "; ".join(problems[:6]),
            symbol="<doc>", code="")]


# ======================================================================
# R4 — exception hygiene
# ======================================================================
class ExceptHygieneRule(Rule):
    """A broad except that swallows silently is how the resilience
    ladder loses evidence: ISSUE 2/6 built durable telemetry exactly
    so failures leave a ledger, and a bare `except Exception: pass`
    upstream of it deletes the ledger entry before it exists. Every
    broad/bare handler must re-raise (typed is better), record a
    flight-recorder event, or carry a pragma naming it a benign
    probe."""

    rule_id = "R4"
    name = "except-hygiene"
    doc = "broad/bare excepts that swallow errors silently"

    _RECORDERS = ("telemetry", "resilience", "faults")

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        if not ctx.path.startswith(PKG + "/"):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if self._compliant(node):
                continue
            out.append(self.finding(
                ctx, node,
                "broad except swallows errors silently — record a "
                "telemetry event, raise typed, or pragma "
                "`# gslint: disable=except-hygiene` for a genuinely "
                "benign probe"))
        return out

    @staticmethod
    def _is_broad(type_node) -> bool:
        if type_node is None:
            return True
        names = []
        if isinstance(type_node, ast.Tuple):
            names = [_dotted(e) for e in type_node.elts]
        else:
            names = [_dotted(type_node)]
        return any(n in ("Exception", "BaseException") for n in names)

    def _compliant(self, handler: ast.ExceptHandler) -> bool:
        for node in ast.walk(handler):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                head = dotted.split(".")[0]
                if head in self._RECORDERS and "." in dotted:
                    return True
                if dotted.endswith("record_demotion"):
                    return True
        return False


# ======================================================================
# R5 — thread-shared state
# ======================================================================
class ThreadSharedRule(Rule):
    """The ingress pipeline runs prep on a worker pool while the main
    thread dispatches: module-level mutables in the modules those
    threads execute are shared state. Each one must either be
    accessed under a module-level Lock somewhere (the
    `with _X_LOCK:` discipline utils/resilience models) or carry a
    pragma declaring it thread-confined / benignly idempotent."""

    rule_id = "R5"
    name = "thread-shared"
    doc = ("module-level mutables in threaded modules without a "
           "lock-guarded access pattern")

    # modules executed by (or memoizing under) pipeline worker threads
    THREADED = (
        PKG + "/ops/ingress_pipeline.py",
        PKG + "/ops/autotune.py",
        PKG + "/ops/triangles.py",
        PKG + "/ops/windowed_reduce.py",
        PKG + "/ops/delta_egress.py",
        PKG + "/parallel/sharded.py",
        PKG + "/utils/telemetry.py",
        PKG + "/utils/metrics.py",
        PKG + "/utils/costmodel.py",
        PKG + "/utils/tracing.py",
        PKG + "/utils/resilience.py",
        PKG + "/utils/faults.py",
        PKG + "/utils/interning.py",
        # the serving front-end's connection/tail/pump threads and
        # the journal they append through (ISSUE 12)
        PKG + "/utils/wal.py",
        PKG + "/core/serve.py",
        # the admission sanitizer + dead-letter journal: serve
        # connection threads and the pump both reject (ISSUE 15)
        PKG + "/utils/sanitize.py",
        # the async pump: the dedicated pump thread runs cohort
        # dispatch (and the resident mailbox) concurrently with the
        # ingest-side connection/tail threads (ISSUE 18)
        PKG + "/core/tenancy.py",
        PKG + "/ops/resident_engine.py",
        PKG + "/utils/latency.py",
        PKG + "/ops/scan_analytics.py",
        # the provenance ledger: every finalize owner appends — serve
        # connection threads, the async pump, the driver (ISSUE 20)
        PKG + "/utils/provenance.py",
    )

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        if ctx.path not in self.THREADED:
            return []
        mutables: Dict[str, ast.Assign] = {}
        locks: Set[str] = set()
        for node in ctx.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            dotted = _dotted(node.value.func) \
                if isinstance(node.value, ast.Call) else ""
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                if dotted.endswith("Lock") or dotted.endswith("RLock"):
                    locks.add(t.id)
                elif JitPurityRule._is_mutable_literal(node.value):
                    mutables[t.id] = node
        guarded: Set[str] = set()
        written: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.With):
                ctx_names = {_dotted(item.context_expr).split(".")[0]
                             for item in node.items}
                if ctx_names & locks:
                    for inner in ast.walk(node):
                        if isinstance(inner, ast.Name) \
                                and inner.id in mutables:
                            guarded.add(inner.id)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                written |= self._mutated_names(node, set(mutables))
        out: List[Finding] = []
        for name, node in sorted(mutables.items()):
            if name in guarded or name not in written:
                # never mutated from function scope = a read-only
                # table, not shared state
                continue
            out.append(self.finding(
                ctx, node,
                "module-level mutable `%s` in a threaded module is "
                "never accessed under a module Lock — guard it "
                "(`with <LOCK>:`) or pragma it thread-confined with "
                "the reason" % name))
        return out

    _MUTATORS = {"append", "add", "update", "setdefault", "pop",
                 "clear", "extend", "remove", "insert", "popleft",
                 "appendleft"}

    @classmethod
    def _mutated_names(cls, fn, candidates: Set[str]) -> Set[str]:
        """Names from `candidates` this function mutates: subscript/
        aug assignment, a mutating method call, or a `global` rebind."""
        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.Delete)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [getattr(node, "target", None)] \
                    if not isinstance(node, ast.Delete) else node.targets
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in candidates:
                        out.add(t.value.id)
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in candidates \
                    and node.func.attr in cls._MUTATORS:
                out.add(node.func.value.id)
            if isinstance(node, ast.Global):
                out |= set(node.names) & candidates
        return out


# ======================================================================
# R6 — checkpoint symmetry
# ======================================================================
class CheckpointSymmetryRule(Rule):
    """A key written by `state_dict` but never read by
    `load_state_dict` (or vice versa) is state that silently fails to
    survive a kill→resume — the exact failure class the ISSUE-2/6
    checkpoint ladder exists to prevent. Compared per class, only
    when BOTH methods are defined on the class (inherited halves are
    covered where they're defined)."""

    rule_id = "R6"
    name = "ckpt-symmetry"
    doc = "state_dict/load_state_dict key-set mismatches per class"

    def check_module(self, ctx: ModuleCtx) -> List[Finding]:
        if not ctx.path.startswith(PKG + "/"):
            return []
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            save = load = None
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef):
                    if sub.name == "state_dict":
                        save = sub
                    elif sub.name == "load_state_dict":
                        load = sub
            if save is None or load is None:
                continue
            saved = self._saved_keys(save)
            loaded = self._loaded_keys(load)
            if not saved or not loaded:
                continue  # fully dynamic formats: nothing provable
            for key, knode in sorted(saved.items()):
                if key not in loaded:
                    out.append(self.finding(
                        ctx, knode,
                        "%s.state_dict writes key %r but "
                        "load_state_dict never reads it — dead state "
                        "or a missed restore" % (node.name, key)))
            for key, knode in sorted(loaded.items()):
                if key not in saved:
                    out.append(self.finding(
                        ctx, knode,
                        "%s.load_state_dict reads key %r that "
                        "state_dict never writes — a fresh checkpoint "
                        "cannot satisfy it" % (node.name, key)))
        return out

    @staticmethod
    def _saved_keys(fn: ast.FunctionDef) -> Dict[str, ast.AST]:
        """String keys the serializer produces: dict-literal keys and
        `X["k"] = ...` stores on locals (nested payload dicts under a
        single top-level key count too — load reads them through the
        same names)."""
        keys: Dict[str, ast.AST] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Dict):
                for k in node.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        keys.setdefault(k.value, k)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets \
                    if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.slice, ast.Constant) \
                            and isinstance(t.slice.value, str):
                        keys.setdefault(t.slice.value, t)
        return keys

    @staticmethod
    def _loaded_keys(fn: ast.FunctionDef) -> Dict[str, ast.AST]:
        """String keys the loader consumes: `state["k"]`,
        `state.get("k"[, d])`, `"k" in state`."""
        keys: Dict[str, ast.AST] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str):
                keys.setdefault(node.slice.value, node)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("get", "pop") \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                keys.setdefault(node.args[0].value, node.args[0])
            elif isinstance(node, ast.Compare) \
                    and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                    and isinstance(node.left, ast.Constant) \
                    and isinstance(node.left.value, str):
                keys.setdefault(node.left.value, node.left)
        return keys


def all_rules() -> List[Rule]:
    return [HostSyncRule(), JitPurityRule(), KnobRegistryRule(),
            ExceptHygieneRule(), ThreadSharedRule(),
            CheckpointSymmetryRule()]

"""gslint — the project invariant checker.

Every perf and robustness PR in this repo depends on hand-enforced
invariants: no host↔device sync outside the sanctioned egress sites
(the dispatch wall is the ROADMAP's top item — BENCH_r05 shows the
round-trip, not compute, is the bottleneck), no impure reads inside
traced code (an `os.environ` read under `jax.jit` silently freezes at
compile time), every `GS_*` knob through the typed registry
(utils/knobs.py), every failure recorded durably, shared state
lock-guarded, checkpoint formats symmetric. Discipline that isn't
mechanically checked erodes; this package is the mechanical check —
an AST-based rule suite specific to this codebase, run as a tier-1
test (tests/test_gslint.py, marker `lint`) so a violation is a test
failure before it is a 2am chip-window debugging session.

Rules (tools/gslint/rules.py):

    R1 host-sync     d2h sync surface (`np.asarray` / `jax.device_get`
                     / `.item()` / `block_until_ready` / `float()`-of-
                     device-expressions) outside the sanctioned
                     egress/finalize/mirror-sync modules
    R2 jit-purity    impure reads (env, telemetry, clocks, module
                     mutables) reachable from jit/scan/shard_map roots
    R3 knob-registry `os.environ` outside utils/knobs.py, unregistered
                     `GS_*` literals, README knob-table drift
    R4 except-hygiene broad/bare excepts that swallow silently
    R5 thread-shared module-level mutables in threaded modules without
                     a lock-guarded access pattern
    R6 ckpt-symmetry state_dict/load_state_dict key-set mismatches

Suppression, narrowest first:

- inline pragma `# gslint: disable=<rule-or-name>[,...]` on the
  flagged line (use for sites with a REASON — put it in a comment);
- file pragma `# gslint: disable-file=<rule>[,...]` anywhere in the
  file's first comment block;
- the committed baseline (tools/gslint/baseline.json): grandfathered
  pre-gslint sites, keyed by (rule, path, enclosing symbol, code
  text) — line-number drift does not invalidate entries, edits to
  the flagged line do. The baseline only ever shrinks: regenerating
  it (`--write-baseline`) to absorb NEW findings defeats the tool,
  and tests/test_gslint.py pins its size.

Usage:
    python -m tools.gslint gelly_streaming_tpu        # human output
    python -m tools.gslint --json -                   # machine output
    python -m tools.gslint --write-baseline           # (re)generate
    python -m tools.gslint --knob-table               # README table

Exit status: number of non-baselined findings, capped at 125 (0 =
clean). The runner reads only committed source files — no runtime
state, no imports of the package under lint (utils/knobs.py is loaded
standalone by file path for the R3 docs diff) — so a soak or bench
run can never change its verdict (pinned by tools/chaos_run.py's
gslint-hermetic leg).
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_TARGET = "gelly_streaming_tpu"
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")

_PRAGMA_RE = re.compile(r"#\s*gslint:\s*disable=([A-Za-z0-9_,\- ]+)")
_FILE_PRAGMA_RE = re.compile(
    r"#\s*gslint:\s*disable-file=([A-Za-z0-9_,\- ]+)")


@dataclass
class Finding:
    """One rule violation at one source location. `symbol` (the
    enclosing def/class qualname) and `code` (the stripped source
    line) — not the line number — form the baseline identity, so
    unrelated edits above a grandfathered site don't resurrect it."""

    rule: str        # "R1".."R6"
    name: str        # rule slug, e.g. "host-sync"
    path: str        # repo-relative posix path
    line: int
    col: int
    message: str
    symbol: str = "<module>"
    code: str = ""
    baselined: bool = False

    def key(self):
        return (self.rule, self.path, self.symbol, self.code)

    def to_json(self) -> dict:
        return {
            "rule": self.rule, "name": self.name, "path": self.path,
            "line": self.line, "col": self.col,
            "message": self.message, "symbol": self.symbol,
            "code": self.code, "baselined": self.baselined,
        }

    def render(self) -> str:
        mark = "  [baseline]" if self.baselined else ""
        return "%s:%d:%d: %s[%s] %s (in %s)%s" % (
            self.path, self.line, self.col, self.rule, self.name,
            self.message, self.symbol, mark)


class Rule:
    """One invariant. Subclasses set `rule_id`/`name`/`doc` and
    implement `check_module` (per-file findings) and/or
    `check_project` (whole-tree findings, e.g. the README docs
    diff)."""

    rule_id = "R0"
    name = "base"
    doc = ""

    def check_module(self, ctx: "ModuleCtx") -> List["Finding"]:
        return []

    def check_project(self, ctxs: Sequence["ModuleCtx"],
                      repo: str) -> List["Finding"]:
        return []

    def finding(self, ctx: "ModuleCtx", node, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(
            rule=self.rule_id, name=self.name, path=ctx.path,
            line=line, col=col, message=message,
            symbol=ctx.symbol_at(line),
            code=ctx.code_at(line))


@dataclass
class ModuleCtx:
    """Parsed view of one source file handed to every rule: the AST,
    the raw lines, per-line pragma sets, and a line→enclosing-symbol
    index (built once; rules are read-only consumers)."""

    path: str                 # repo-relative posix
    tree: ast.AST
    lines: List[str]
    pragmas: Dict[int, set] = field(default_factory=dict)
    file_pragmas: set = field(default_factory=set)
    _symbols: List[tuple] = field(default_factory=list)

    @classmethod
    def load(cls, abspath: str, relpath: str) -> Optional["ModuleCtx"]:
        with open(abspath, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=relpath)
        except SyntaxError:
            return None  # not ours to judge; python itself will
        ctx = cls(path=relpath.replace(os.sep, "/"), tree=tree,
                  lines=source.splitlines())
        for i, text in enumerate(ctx.lines, 1):
            m = _PRAGMA_RE.search(text)
            if m:
                ctx.pragmas[i] = {t.strip() for t in
                                  m.group(1).split(",") if t.strip()}
            m = _FILE_PRAGMA_RE.search(text)
            if m:
                ctx.file_pragmas |= {t.strip() for t in
                                     m.group(1).split(",") if t.strip()}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                end = getattr(node, "end_lineno", node.lineno)
                ctx._symbols.append((node.lineno, end, node.name,
                                     isinstance(node, ast.ClassDef)))
        ctx._symbols.sort()
        return ctx

    def code_at(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def symbol_at(self, line: int) -> str:
        """Innermost enclosing def/class name chain ('Cls.meth'), or
        '<module>'."""
        chain = []
        for start, end, name, _is_cls in self._symbols:
            if start <= line <= end:
                chain.append((start, name))
        if not chain:
            return "<module>"
        chain.sort()
        return ".".join(name for _s, name in chain[-2:])

    def suppressed(self, f: Finding) -> bool:
        tags = self.pragmas.get(f.line, set()) | self.file_pragmas
        return bool(tags & {f.rule, f.name, "all"})


# ----------------------------------------------------------------------
# baseline
# ----------------------------------------------------------------------
def load_baseline(path: str = BASELINE_PATH) -> Dict[tuple, int]:
    """Counted multiset of grandfathered finding keys. Missing file =
    empty baseline (the self-check fixtures run baseline-free)."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[tuple, int] = {}
    for e in data.get("entries", []):
        key = (e["rule"], e["path"], e["symbol"], e["code"])
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def apply_baseline(findings: List[Finding],
                   baseline: Dict[tuple, int]) -> None:
    """Mark findings covered by the baseline, consuming counts so N
    grandfathered copies of a line never absolve an N+1th."""
    budget = dict(baseline)
    for f in findings:
        if budget.get(f.key(), 0) > 0:
            budget[f.key()] -= 1
            f.baselined = True


def write_baseline(findings: List[Finding],
                   path: str = BASELINE_PATH) -> int:
    counts: Dict[tuple, int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    entries = [
        {"rule": k[0], "path": k[1], "symbol": k[2], "code": k[3],
         "count": n}
        for k, n in sorted(counts.items())
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=1)
        f.write("\n")
    return len(entries)


# ----------------------------------------------------------------------
# runner
# ----------------------------------------------------------------------
def iter_sources(target: str, repo: str = REPO):
    """Yield (abspath, repo-relative path) for every committed .py
    under `target` (itself repo-relative or absolute)."""
    root = target if os.path.isabs(target) else os.path.join(repo,
                                                             target)
    if os.path.isfile(root):
        yield root, os.path.relpath(root, repo)
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__"
                             and not d.startswith("."))
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                ap = os.path.join(dirpath, fn)
                yield ap, os.path.relpath(ap, repo)


def run_lint(targets: Sequence[str] = (DEFAULT_TARGET,),
             rules: Optional[Sequence[Rule]] = None,
             baseline_path: Optional[str] = BASELINE_PATH,
             repo: str = REPO) -> List[Finding]:
    """Lint `targets`, returning ALL findings (pragma-suppressed ones
    dropped, baselined ones marked). Deterministic: sorted file walk,
    stable rule order, no clocks, no randomness, no imports of the
    code under lint."""
    from . import rules as rules_mod

    if rules is None:
        rules = rules_mod.all_rules()
    ctxs: List[ModuleCtx] = []
    for target in targets:
        for abspath, rel in iter_sources(target, repo):
            ctx = ModuleCtx.load(abspath, rel)
            if ctx is not None:
                ctxs.append(ctx)
    findings: List[Finding] = []
    by_path = {c.path: c for c in ctxs}
    for rule in rules:
        for ctx in ctxs:
            for f in rule.check_module(ctx):
                if not ctx.suppressed(f):
                    findings.append(f)
        for f in rule.check_project(ctxs, repo):
            ctx = by_path.get(f.path)
            if ctx is None or not ctx.suppressed(f):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    if baseline_path:
        apply_baseline(findings, load_baseline(baseline_path))
    return findings


def report_json(findings: List[Finding],
                targets: Sequence[str]) -> dict:
    per_rule: Dict[str, int] = {}
    for f in findings:
        if not f.baselined:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
    return {
        "version": 1,
        "tool": "gslint",
        "targets": list(targets),
        "findings": [f.to_json() for f in findings],
        "counts": {
            "total": len(findings),
            "baselined": sum(1 for f in findings if f.baselined),
            "new": sum(1 for f in findings if not f.baselined),
            "per_rule": per_rule,
        },
    }


# ----------------------------------------------------------------------
# report schema (tools/perf_schema.py conventions: known shapes are
# enforced, unknown top-level keys are allowed)
# ----------------------------------------------------------------------
_FINDING_KEYS = {
    "rule": str, "name": str, "path": str, "line": int, "col": int,
    "message": str, "symbol": str, "code": str, "baselined": bool,
}


def validate_report(obj) -> List[str]:
    """Shape-check one report_json() payload; returns problem strings
    (empty = clean). Same contract style as tools/perf_schema.py:
    consumers (CI diffing, trend dashboards) must never crash on a
    committed report."""
    errors: List[str] = []
    if not isinstance(obj, dict):
        return ["report: not an object"]
    if obj.get("version") != 1:
        errors.append("report: version must be 1")
    if obj.get("tool") != "gslint":
        errors.append("report: tool must be 'gslint'")
    if not isinstance(obj.get("targets"), list):
        errors.append("report: targets must be a list")
    findings = obj.get("findings")
    if not isinstance(findings, list):
        errors.append("report: findings must be a list")
        findings = []
    for i, f in enumerate(findings):
        if not isinstance(f, dict):
            errors.append("findings[%d]: not an object" % i)
            continue
        for key, kind in _FINDING_KEYS.items():
            if key not in f:
                errors.append("findings[%d]: missing %s" % (i, key))
            elif not isinstance(f[key], kind):
                errors.append("findings[%d].%s: expected %s, got %r"
                              % (i, key, kind.__name__, f[key]))
        rule = f.get("rule")
        if isinstance(rule, str) and not re.fullmatch(r"R[1-6]", rule):
            errors.append("findings[%d].rule: unknown rule %r"
                          % (i, rule))
    counts = obj.get("counts")
    if not isinstance(counts, dict):
        errors.append("report: counts must be an object")
    else:
        for key in ("total", "baselined", "new"):
            if not isinstance(counts.get(key), int):
                errors.append("counts.%s: expected int" % key)
        if not isinstance(counts.get("per_rule"), dict):
            errors.append("counts.per_rule: expected object")
        elif isinstance(counts.get("new"), int):
            if sum(counts["per_rule"].values()) != counts["new"]:
                errors.append("counts.per_rule: does not sum to new")
    return errors

"""CLI for the project invariant checker.

    python -m tools.gslint [TARGET ...]       lint (default: the package)
    python -m tools.gslint --json -           machine-readable report
    python -m tools.gslint --write-baseline   regenerate the baseline
    python -m tools.gslint --knob-table       print the README GS_* table
    python -m tools.gslint --list-rules       rule ids and summaries

Exit status = number of non-baselined findings (capped at 125).
"""

from __future__ import annotations

import argparse
import json
import sys

from . import (BASELINE_PATH, DEFAULT_TARGET, report_json, run_lint,
               write_baseline)
from . import rules as rules_mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="gslint",
                                 description=__doc__.split("\n")[0])
    ap.add_argument("targets", nargs="*", default=[DEFAULT_TARGET])
    ap.add_argument("--json", metavar="PATH",
                    help="write the JSON report to PATH ('-' = stdout)")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="baseline file (default tools/gslint/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report grandfathered findings too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write ALL current findings as the new "
                         "baseline (policy: only ever shrink it)")
    ap.add_argument("--knob-table", action="store_true",
                    help="print the README knob table rendered from "
                         "utils/knobs.py and exit")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.knob_table:
        print(rules_mod.KnobRegistryRule.registry().render_table())
        return 0
    if args.list_rules:
        for rule in rules_mod.all_rules():
            print("%s  %-14s %s" % (rule.rule_id, rule.name, rule.doc))
        return 0

    targets = args.targets or [DEFAULT_TARGET]
    baseline = None if (args.no_baseline or args.write_baseline) \
        else args.baseline
    findings = run_lint(targets, baseline_path=baseline)

    if args.write_baseline:
        n = write_baseline(findings, args.baseline)
        print("gslint: baseline written: %d entries (%d findings) -> %s"
              % (n, len(findings), args.baseline))
        return 0

    if args.json:
        payload = json.dumps(report_json(findings, targets), indent=1)
        if args.json == "-":
            print(payload)
        else:
            with open(args.json, "w", encoding="utf-8") as f:
                f.write(payload + "\n")
    new = [f for f in findings if not f.baselined]
    shown = findings if args.no_baseline else new
    for f in shown:
        print(f.render())
    print("gslint: %d finding(s), %d baselined, %d new"
          % (len(findings), len(findings) - len(new), len(new)))
    return min(125, len(new))


if __name__ == "__main__":
    sys.exit(main())

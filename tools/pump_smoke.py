#!/usr/bin/env python
"""CI gate for the async serving pump (tools/ci_check.sh [11/11]):

an armed loopback run under GS_PUMP=async must

  1. produce per-tenant summary digests BYTE-IDENTICAL to the
     GS_PUMP=sync legacy path on the same streams (the pump can never
     silently drift the serving semantics), and
  2. actually OVERLAP ingest with dispatch: at least one feed must be
     accepted while a dispatch is in flight (`overlap_feeds` > 0,
     counted at the ingest lock while the pump thread's busy flag is
     set). A vacuous pass — async mode that quietly serializes — fails
     the gate. Overlap is forced deterministically by hanging one
     dispatch (a `tenant_prep` hang fault) and feeding through it.

Also pins the sliding defaults: a GS_SLIDE-armed SlidingSummaryEngine
at slide == edge_bucket must equal the tumbling engine digest (one
pane per window = the legacy path), in seconds not minutes.
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from bench import make_stream  # noqa: E402
from tools.tenancy_ab import digest_summaries, scoped_env  # noqa: E402

EB, VB = 512, 1024


def _feed_retry(cli, tid, s, d):
    deadline = time.monotonic() + 60
    while True:
        r = cli.feed(tid, s, d)
        if r.get("ok"):
            return
        if r.get("error") != "TenantBackpressure" \
                or time.monotonic() > deadline:
            raise RuntimeError("feed refused: %s" % r)
        time.sleep(r.get("retry_after_s", 0.05))


def _serve_digests(streams, mode: str, hang_one: bool = False):
    """Feed `streams` through a loopback server under GS_PUMP=`mode`;
    returns (per-tenant digests, overlap_feeds). With hang_one, one
    dispatch is hung mid-run and a window is fed through it — the
    deterministic overlap proof."""
    from gelly_streaming_tpu.core.serve import ServeClient, StreamServer
    from gelly_streaming_tpu.core.tenancy import TenantCohort
    from gelly_streaming_tpu.utils import faults

    with scoped_env(GS_PUMP=mode):
        cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
        srv = StreamServer(cohort, port=0).start()
        try:
            cli = ServeClient(srv.port, timeout=60)
            for tid in streams:
                cli.admit(tid)
            cursors = {tid: 0 for tid in streams}
            fed_rounds = 0
            live = True
            while live:
                live = False
                for tid, (s, d) in streams.items():
                    c = cursors[tid]
                    if c >= len(s):
                        continue
                    hi = min(c + EB, len(s))
                    if hang_one and fed_rounds == 1 and c == EB:
                        # round 2, first tenant: hang the NEXT
                        # dispatch and land this feed inside it
                        with faults.inject(faults.FaultSpec(
                                site="tenant_prep", on_call=1,
                                action="hang", seconds=0.5)):
                            _feed_retry(cli, tid, s[c:hi], d[c:hi])
                            time.sleep(0.1)  # let the pump pick it up
                    else:
                        _feed_retry(cli, tid, s[c:hi], d[c:hi])
                    cursors[tid] = hi
                    live = True
                fed_rounds += 1
                if mode == "sync":
                    cli.pump()
            cli.close()
            srv.drain(deadline_s=60)
            digests = {tid: digest_summaries(
                [row["summary"] for row in rows])
                for tid, rows in srv.results.items()}
            return digests, srv._stats.get("overlap_feeds", 0)
        finally:
            srv.close()


def pump_gate() -> int:
    streams = {}
    for i in range(2):
        n = 3 * EB - (EB // 4 if i else 0)  # one ragged tenant
        s, d = make_stream(n, VB, seed=31 + i)
        streams["t%d" % i] = (s.astype(np.int32), d.astype(np.int32))
    want, _ = _serve_digests(streams, "sync")
    got, overlap = _serve_digests(streams, "async", hang_one=True)
    bad = [t for t in streams if got.get(t) != want[t]]
    if bad:
        print("pump smoke FAILED: tenants %s diverged from the sync "
              "legacy path (async %s vs sync %s)"
              % (bad, got, want), file=sys.stderr)
        return 1
    if overlap < 1:
        print("pump smoke FAILED: GS_PUMP=async never overlapped "
              "ingest with dispatch (overlap_feeds=0) — the pump "
              "thread is serializing", file=sys.stderr)
        return 1
    print("pump smoke ok: async ≡ sync per tenant (%s), "
          "%d overlapped feed(s)"
          % (", ".join(sorted(want.values())), overlap), flush=True)
    return 0


def sliding_gate() -> int:
    from gelly_streaming_tpu.ops.scan_analytics import (
        SlidingSummaryEngine, StreamSummaryEngine)

    n = 3 * EB + EB // 4
    s, d = make_stream(n, VB, seed=37)
    s, d = s.astype(np.int32), d.astype(np.int32)
    want = StreamSummaryEngine(edge_bucket=EB,
                               vertex_bucket=VB).process(s, d)
    got = SlidingSummaryEngine(edge_bucket=EB, vertex_bucket=VB,
                               slide=EB).process(s, d)
    if digest_summaries(got) != digest_summaries(want):
        print("pump smoke FAILED: slide == edge_bucket is not the "
              "tumbling digest (%s vs %s)"
              % (digest_summaries(got), digest_summaries(want)),
              file=sys.stderr)
        return 1
    print("sliding smoke ok: slide==size ≡ tumbling (%s, %d windows)"
          % (digest_summaries(got), len(got)), flush=True)
    return 0


def main() -> int:
    os.environ["GS_AUTOTUNE"] = "0"
    rc = pump_gate()
    if rc:
        return rc
    return sliding_gate()


if __name__ == "__main__":
    sys.exit(main())

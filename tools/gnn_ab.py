#!/usr/bin/env python
"""Windowed-GNN A/B: is the device GNN round (ops/gnn_window) worth
its dispatches — at EXACT feature-slab parity with the numpy twin?

Three probes, each a JSON row:

  gnn_engine — GnnSummaryEngine (fused lax.scan over chunked windows)
              vs GnnHostEngine (the numpy bit-exactness oracle) on
              the same stream: sha256 over the summary stream AND the
              final [vb, F] feature slab must match before any
              speedup is claimed. The lattice exactness argument
              (module docstring of ops/gnn_window) is what makes this
              an equality, not a tolerance.
  gnn_cohort — core/tenancy.GnnTenantCohort folding N tenants'
              windows in ONE vmapped dispatch vs N sequential
              GnnSummaryEngine runs, per-tenant slab + summary
              parity, one row per N — the acceptance evidence at
              N ∈ {1, 8} (the N=1 row is the honest no-gain floor).
  gnn_pallas — the fused Pallas GNN kernel (GS_GNN_PALLAS=on) vs the
              XLA gather/segment-sum round (pinned off). Off-TPU this
              runs in interpret mode and the row carries
              `interpret: true`; pallas_window.resolve_gnn_pallas
              ignores interpret rows for adoption, so those rows are
              PARITY evidence, not speed evidence.

Timing is median-of-3 with min/max dispersion in the row (the ingress
A/B's flip-flop taught us a single draw is load noise). GS_AUTOTUNE
is pinned OFF inside the probes.

`--commit` merges the rows into PERF.json (backend-matched) and
PERF_<backend>.json under `gnn_ab`, AND commits the `gnn` cost
section (gnn_cost_section — the same helper tools/profile_kernels.py
section_gnn runs): the armed cost-observatory rows for the GNN
programs with the stated arithmetic intensity beside the measured
throughput. The intensity claim is the point of the workload — the
dense update's 2·(vb+1)·F² FLOPs put these programs past every
existing gather program's 0.25–0.28 FLOPs/byte — and it is stated
honestly: on CPU the measured rate stays far below the model's bound
either way, and the row says which bound the MODEL predicts, not
what the backend achieved.
"""

import hashlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from bench import make_stream  # noqa: E402
from tools.egress_ab import _dispersion, timed_stats  # noqa: E402


def digest_summaries(summaries) -> str:
    """sha256 over the summary-dict stream (every field, in window
    order) — the per-stream parity identity."""
    h = hashlib.sha256()
    for s in summaries:
        h.update(json.dumps(s, sort_keys=True).encode())
    return h.hexdigest()[:16]


def digest_slab(slab: np.ndarray) -> str:
    """sha256 over the exact bytes of the [vb, F] feature slab — the
    carry-state parity identity (summaries alone can't see a slab
    divergence that happens to preserve the checksum)."""
    return hashlib.sha256(
        np.ascontiguousarray(slab, np.float32).tobytes()
    ).hexdigest()[:16]


def make_tenant_streams(n_tenants: int, windows: int, eb: int,
                        vb: int, ragged: bool = True):
    """One deterministic power-law stream per tenant; ragged lengths
    (a short partial tail on some tenants) exercise the window-axis
    padding the empty-window-holds rule exists for."""
    streams = {}
    for i in range(n_tenants):
        n = windows * eb
        if ragged and i % 3 == 2:
            n -= eb // 3  # partial final window
        s, d = make_stream(n, vb, seed=100 + i)
        streams["t%02d" % i] = (s.astype(np.int32), d.astype(np.int32))
    return streams


def _weights(F: int):
    """Deterministic non-trivial dense layer (snapped by the engines):
    a mixing matrix, not the identity default — parity on the
    identity would not exercise the matmul at all."""
    rng = np.random.RandomState(42)
    return rng.randn(F, F) * 0.3, rng.randn(F) * 0.1


def run_engine(cls, eb, vb, F, s, d):
    """One engine-tier run: seed deterministic features + weights,
    fold the stream, return (summaries, final slab)."""
    from gelly_streaming_tpu.ops import gnn_window as gw

    eng = cls(eb, vb, feature_dim=F)
    eng.set_weights(*_weights(F))
    eng.load_feature_units(gw.default_features(vb, F, seed=1))
    out = eng.process(s, d)
    return out, eng.state()


def run_cohort(streams, eb, vb, F):
    """The cohort side: admit everyone with per-tenant seeds, feed in
    arrival order, pump each round, close. Returns per-tenant
    (summaries, final slab)."""
    from gelly_streaming_tpu.core.tenancy import GnnTenantCohort
    from gelly_streaming_tpu.ops import gnn_window as gw

    co = GnnTenantCohort(eb, vb, feature_dim=F)
    co.set_weights(*_weights(F))
    out = {tid: [] for tid in streams}
    for i, tid in enumerate(sorted(streams)):
        co.admit(tid, feature_units=gw.default_features(vb, F,
                                                        seed=i))
    cursors = {tid: 0 for tid in streams}
    live = True
    while live:
        live = False
        for tid, (s, d) in streams.items():
            c = cursors[tid]
            if c >= len(s):
                continue
            hi = min(c + 2 * eb, len(s))
            co.feed(tid, s[c:hi], d[c:hi])
            cursors[tid] = hi
            live = True
        for tid, res in co.pump().items():
            out[tid].extend(res)
    slabs = {}
    for tid in streams:
        slabs[tid] = co.state(tid) if not co.queued_edges(tid) \
            else None
        out[tid].extend(co.close(tid))
    return out, slabs


def cohort_oracle(streams, eb, vb, F):
    """N sequential GnnSummaryEngine runs with the cohort's
    per-tenant seeds — the baseline being measured AND the parity
    oracle."""
    from gelly_streaming_tpu.ops import gnn_window as gw

    out, slabs = {}, {}
    for i, tid in enumerate(sorted(streams)):
        eng = gw.GnnSummaryEngine(eb, vb, feature_dim=F)
        eng.set_weights(*_weights(F))
        eng.load_feature_units(gw.default_features(vb, F, seed=i))
        s, d = streams[tid]
        out[tid] = eng.process(s, d)
        slabs[tid] = eng.state()
    return out, slabs


class scoped_env:
    """Pin GS_* knobs for one probe side and restore afterwards,
    resetting the memoised Pallas resolvers so the pin is seen
    (resolve_* caches the auto decision per process)."""

    def __init__(self, **pins):
        self.pins = pins
        self._old = {}

    def _reset(self):
        from gelly_streaming_tpu.ops import pallas_window
        pallas_window._reset_pallas_window()

    def __enter__(self):
        for k, v in self.pins.items():
            self._old[k] = os.environ.get(k)
            os.environ[k] = v
        self._reset()
        return self

    def __exit__(self, *exc):
        for k, old in self._old.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        self._reset()
        return False


def probe_engine(jax, eb, vb, F, windows, results) -> None:
    """gnn_engine: device scan vs the numpy twin."""
    from gelly_streaming_tpu.ops import gnn_window as gw

    n = windows * eb - eb // 3  # ragged tail on purpose
    s, d = make_stream(n, vb, seed=7)
    s, d = s.astype(np.int32), d.astype(np.int32)
    got, slab = run_engine(gw.GnnSummaryEngine, eb, vb, F, s, d)
    want, wslab = run_engine(gw.GnnHostEngine, eb, vb, F, s, d)
    parity = (digest_summaries(got) == digest_summaries(want)
              and digest_slab(slab) == digest_slab(wslab))
    dev = timed_stats(
        lambda: run_engine(gw.GnnSummaryEngine, eb, vb, F, s, d),
        reps=3, warmup=0)
    host = timed_stats(
        lambda: run_engine(gw.GnnHostEngine, eb, vb, F, s, d),
        reps=3, warmup=0)
    ef = n * F  # edge-features: the workload's throughput unit
    row = {
        "probe": "gnn_engine",
        "backend": jax.default_backend(),
        "eb": eb, "vb": vb, "feature_dim": F,
        "num_edges": n, "windows": -(-n // eb),
        "engine_edges_per_s": round(n / dev[0]),
        "host_edges_per_s": round(n / host[0]),
        "gnn_edge_features_per_s": round(ef / dev[0]),
        "parity": bool(parity),
        "slab_digest": digest_slab(slab),
        "summary_digest": digest_summaries(got),
    }
    _dispersion(row, "engine", dev)
    _dispersion(row, "host", host)
    if parity:
        row["speedup"] = round(host[0] / dev[0], 3)
        row["speedup_worst"] = round(host[1] / dev[2], 3)
        row["speedup_best"] = round(host[2] / dev[1], 3)
    else:
        print("PARITY FAILURE (gnn_engine): device slab/summaries "
              "diverged from the numpy twin", file=sys.stderr)
    results.append(row)
    print(json.dumps(row), flush=True)


def probe_cohort(jax, eb, vb, F, windows, n_tenants,
                 results) -> None:
    """gnn_cohort: one vmapped N-tenant dispatch vs N sequential
    engines, per-tenant slab + summary parity."""
    streams = make_tenant_streams(n_tenants, windows, eb, vb)
    got, gslabs = run_cohort(streams, eb, vb, F)
    want, wslabs = cohort_oracle(streams, eb, vb, F)
    parity = all(
        digest_summaries(got[t]) == digest_summaries(want[t])
        and (gslabs[t] is None
             or digest_slab(gslabs[t]) == digest_slab(wslabs[t]))
        for t in streams)
    coh = timed_stats(lambda: run_cohort(streams, eb, vb, F),
                      reps=3, warmup=0)
    seq = timed_stats(lambda: cohort_oracle(streams, eb, vb, F),
                      reps=3, warmup=0)
    total = sum(len(s) for s, _d in streams.values())
    row = {
        "probe": "gnn_cohort",
        "backend": jax.default_backend(),
        "tenants": n_tenants,
        "eb": eb, "vb": vb, "feature_dim": F,
        "num_edges": total,
        "windows": sum(-(-len(s) // eb)
                       for s, _d in streams.values()),
        "cohort_edges_per_s": round(total / coh[0]),
        "sequential_edges_per_s": round(total / seq[0]),
        "gnn_edge_features_per_s": round(total * F / coh[0]),
        "parity": bool(parity),
        "tenant_digests": {t: digest_summaries(got[t])
                           for t in sorted(streams)},
    }
    _dispersion(row, "cohort", coh)
    _dispersion(row, "sequential", seq)
    if parity:
        row["speedup"] = round(seq[0] / coh[0], 3)
        row["speedup_worst"] = round(seq[1] / coh[2], 3)
        row["speedup_best"] = round(seq[2] / coh[1], 3)
    else:
        bad = [t for t in streams
               if digest_summaries(got[t]) != digest_summaries(want[t])]
        print("PARITY FAILURE (gnn_cohort N=%d): tenants %s diverged"
              % (n_tenants, bad), file=sys.stderr)
    results.append(row)
    print(json.dumps(row), flush=True)


def probe_pallas(jax, eb, vb, F, windows, results) -> None:
    """gnn_pallas: the fused kernel (pinned on) vs the XLA round
    (pinned off), slab + summary parity. The kernel must actually
    have been selected — a silent gate decline fails the probe
    instead of measuring XLA against itself."""
    from gelly_streaming_tpu.ops import gnn_window as gw

    n = windows * eb - eb // 3
    s, d = make_stream(n, vb, seed=7)
    s, d = s.astype(np.int32), d.astype(np.int32)
    on_tpu = jax.default_backend() == "tpu"

    with scoped_env(GS_GNN_PALLAS="off"):
        want, wslab = run_engine(gw.GnnSummaryEngine, eb, vb, F,
                                 s, d)
        xla = timed_stats(
            lambda: run_engine(gw.GnnSummaryEngine, eb, vb, F, s, d),
            reps=3, warmup=0)
    with scoped_env(GS_GNN_PALLAS="on"):
        eng = gw.GnnSummaryEngine(eb, vb, feature_dim=F)
        if not eng._pallas:
            print("PROBE FAILURE (gnn_pallas): GS_GNN_PALLAS=on but "
                  "the kernel was not selected (silent gate decline)",
                  file=sys.stderr)
            results.append({"probe": "gnn_pallas",
                            "backend": jax.default_backend(),
                            "eb": eb, "vb": vb, "feature_dim": F,
                            "parity": False, "selected": False})
            return
        got, slab = run_engine(gw.GnnSummaryEngine, eb, vb, F, s, d)
        pal = timed_stats(
            lambda: run_engine(gw.GnnSummaryEngine, eb, vb, F, s, d),
            reps=3, warmup=0)
    parity = (digest_summaries(got) == digest_summaries(want)
              and digest_slab(slab) == digest_slab(wslab))
    row = {
        "probe": "gnn_pallas",
        "backend": jax.default_backend(),
        "eb": eb, "vb": vb, "feature_dim": F,
        "num_edges": n, "windows": -(-n // eb),
        "pallas_edges_per_s": round(n / pal[0]),
        "xla_edges_per_s": round(n / xla[0]),
        "parity": bool(parity),
        "selected": True,
        "slab_digest": digest_slab(slab),
    }
    if not on_tpu:
        row["interpret"] = True
    _dispersion(row, "pallas", pal)
    _dispersion(row, "xla", xla)
    if parity:
        row["speedup"] = round(xla[0] / pal[0], 3)
        row["speedup_worst"] = round(xla[1] / pal[2], 3)
        row["speedup_best"] = round(xla[2] / pal[1], 3)
    else:
        print("PARITY FAILURE (gnn_pallas): fused kernel diverged "
              "from the XLA round", file=sys.stderr)
    results.append(row)
    print(json.dumps(row), flush=True)


def gnn_cost_section(eb: int = 32768, vb: int = 65536,
                     F: int = None, edges: int = None) -> dict:
    """The `gnn` cost-observatory section (shared by --commit here
    and tools/profile_kernels.py section_gnn): run the GNN engine
    armed on the acceptance shape, assert digest parity against a
    disarmed run AND the host twin, and return the per-program
    analytic rows — each stating its arithmetic intensity — beside
    the measured throughput. The honesty clause: the intensity is the
    STATED model's (flops/bytes of the analytic slab model, computed
    by utils/costmodel.classify), not a measured counter; on CPU the
    achieved rate stays bytes-bound regardless, and the row carries
    both numbers so PERF.md can say so."""
    import jax

    from gelly_streaming_tpu.ops import gnn_window as gw
    from gelly_streaming_tpu.utils import costmodel, telemetry

    from bench import make_stream as _mk

    if F is None:
        F = 16
    if edges is None:
        edges = int(os.environ.get("GS_TELEMETRY_EDGES", 524288))
    s, d = _mk(edges, vb)
    s, d = s.astype(np.int32), d.astype(np.int32)

    prev = {k: os.environ.get(k)
            for k in ("GS_COSTMODEL", "GS_TELEMETRY")}
    try:
        os.environ["GS_COSTMODEL"] = "0"
        os.environ["GS_TELEMETRY"] = "0"
        base, base_slab = run_engine(gw.GnnSummaryEngine, eb, vb, F,
                                     s, d)
        twin, twin_slab = run_engine(gw.GnnHostEngine, eb, vb, F,
                                     s, d)
        os.environ["GS_COSTMODEL"] = "1"
        os.environ["GS_TELEMETRY"] = "1"
        telemetry.reset()
        costmodel.reset()
        t = timed_stats(lambda: run_engine(gw.GnnSummaryEngine, eb,
                                           vb, F, s, d),
                        reps=1, warmup=0)
        armed, armed_slab = run_engine(gw.GnnSummaryEngine, eb, vb,
                                       F, s, d)
        parity = (digest_summaries(base) == digest_summaries(armed)
                  == digest_summaries(twin)
                  and digest_slab(base_slab) == digest_slab(armed_slab)
                  == digest_slab(twin_slab))
        if not parity:
            raise AssertionError(
                "gnn cost section: armed/disarmed/host digests "
                "diverged — the observatory must observe, never "
                "participate")
        rows = [r for r in costmodel.report()
                if (r.get("program") or "").startswith("gnn")]
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        telemetry.reset()
        costmodel.reset()
    return {
        "engine": "gnn_scan",
        "backend": jax.default_backend(),
        "edge_bucket": eb,
        "vertex_bucket": vb,
        "feature_dim": F,
        "num_edges": edges,
        "parity": True,
        "edges_per_s": round(edges / t[0]),
        "gnn_edge_features_per_s": round(edges * F / t[0]),
        "programs": rows,
    }


PROBE_NAMES = ("gnn_engine", "gnn_cohort", "gnn_pallas")


def commit_results(results, backend: str, gnn_section=None) -> None:
    """Merge this run's `gnn_ab` rows (and the `gnn` cost section)
    into the committed evidence — the same policy as
    tools/tenancy_ab.py: PERF.json only when its backend label
    matches the live backend, the per-backend archive
    PERF_<backend>.json always. Merge is BY PROBE."""
    ran = {r["probe"] for r in results}
    targets = ((os.path.join(REPO, "PERF.json"), True),
               (os.path.join(REPO, "PERF_%s.json" % backend), False))
    for path, need_match in targets:
        try:
            with open(path) as f:
                cur = json.load(f)
        except (OSError, ValueError):
            cur = {}
        if need_match and cur.get("backend") != backend:
            print("not committing to %s: file backend %r != live %r"
                  % (os.path.basename(path), cur.get("backend"),
                     backend), file=sys.stderr)
            continue
        cur.setdefault("backend", backend)
        kept = [r for r in cur.get("gnn_ab", [])
                if r.get("probe") not in ran]
        cur["gnn_ab"] = kept + results
        if gnn_section is not None:
            cur["gnn"] = gnn_section
        with open(path, "w") as f:
            json.dump(cur, f, indent=2)
        print("committed %d gnn_ab row(s)%s to %s (%d prior row(s) "
              "kept)" % (len(results),
                         " + gnn section" if gnn_section else "",
                         os.path.basename(path), len(kept)),
              flush=True)


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("probes", nargs="*",
                    help="subset of %s to run (default: all)"
                         % (PROBE_NAMES,))
    ap.add_argument("--tenants", type=int,
                    default=int(os.environ.get("GS_AB_TENANTS", 8)))
    ap.add_argument("--windows", type=int,
                    default=int(os.environ.get("GS_AB_WINDOWS", 8)),
                    help="windows per stream")
    ap.add_argument("--eb", type=int,
                    default=int(os.environ.get("GS_AB_EB", 512)))
    ap.add_argument("--vb", type=int,
                    default=int(os.environ.get("GS_AB_VB", 1024)))
    ap.add_argument("--feature-dim", type=int,
                    default=int(os.environ.get("GS_AB_F", 16)))
    ap.add_argument("--commit", action="store_true",
                    help="merge rows into PERF.json (backend-matched) "
                         "and PERF_<backend>.json, plus the `gnn` "
                         "cost section")
    args = ap.parse_args()
    bad = [p for p in args.probes if p not in PROBE_NAMES]
    if bad:
        ap.error("unknown probe(s) %s; valid: %s"
                 % (bad, list(PROBE_NAMES)))
    want = args.probes or list(PROBE_NAMES)

    os.environ["GS_AUTOTUNE"] = "0"

    import jax

    eb, vb, F = args.eb, args.vb, args.feature_dim
    results = []
    if "gnn_engine" in want:
        probe_engine(jax, eb, vb, F, args.windows, results)
    if "gnn_cohort" in want:
        for n in sorted({1, 3, args.tenants}):
            probe_cohort(jax, eb, vb, F, args.windows, n, results)
    if "gnn_pallas" in want:
        probe_pallas(jax, eb, vb, F, args.windows, results)
    out = os.path.join(REPO, "logs",
                       "gnn_ab_%s.json" % jax.default_backend())
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote %s" % out, flush=True)
    if args.commit:
        section = gnn_cost_section()
        commit_results(results, jax.default_backend(), section)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Poison-input hardening smoke — CI gate (tools/ci_check.sh).

An 8-tenant cohort with ONE hostile tenant flooding garbage (byte
soup through `native.parse_edge_bytes`, out-of-range / negative /
int32-overflowing ids, and a poisoned dispatch) must:

  1. keep the 7 healthy tenants' per-tenant summary digests
     BIT-IDENTICAL to a fault-free oracle (the admission sanitizer +
     cohort bulkhead change availability for the hostile stream only,
     never results for anyone else);
  2. quarantine the hostile tenant (durable bulkhead state) instead
     of letting its poisoned dispatch take the cohort down;
  3. record EVERY rejected edge in the dead-letter journal — counts
     and (offset, src, dst) content both reconcile against a
     pure-Python oracle of the sanitizer's policy;
  4. re-inject replay-exactly: after an operator fix (`mod:vb`), the
     DLQ records fed back through tools/dlq_report.reinject produce
     digests identical to feeding the fixed edges directly — source
     offsets restore the ORIGINAL feed order.

Exit 0 = clean. Runs in seconds on the CPU backend.
"""

import hashlib
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from bench import make_stream  # noqa: E402
from gelly_streaming_tpu import native  # noqa: E402
from gelly_streaming_tpu.core.tenancy import TenantCohort  # noqa: E402
from gelly_streaming_tpu.ops.scan_analytics import (  # noqa: E402
    StreamSummaryEngine)
from gelly_streaming_tpu.utils import faults  # noqa: E402
from gelly_streaming_tpu.utils import sanitize  # noqa: E402

EB, VB, NUM_W, N_TENANTS = 256, 512, 4, 8
HOSTILE = "t7"


def digest(summaries) -> str:
    h = hashlib.sha256()
    for s in summaries:
        h.update(json.dumps(s, sort_keys=True).encode())
    return h.hexdigest()[:16]


def hostile_bytes(rng) -> bytes:
    """The hostile tenant's wire payload: random byte soup mixed with
    parseable lines whose ids are garbage — what a buggy (or
    malicious) client actually produces."""
    lines = []
    for i in range(EB):
        r = int(rng.integers(0, 6))
        if r == 0:
            lines.append(bytes(rng.integers(32, 127, 12,
                                            dtype=np.uint8)))
        elif r == 1:
            lines.append(b"%d %d" % (rng.integers(VB, 1 << 40),
                                     rng.integers(0, VB)))
        elif r == 2:
            lines.append(b"%d %d" % (-rng.integers(1, 1 << 20),
                                     rng.integers(0, VB)))
        elif r == 3:
            lines.append(b"nan inf")
        else:
            lines.append(b"%d %d" % (rng.integers(0, VB),
                                     rng.integers(0, VB)))
    return b"\n".join(lines) + b"\n"


def oracle_filter(src, dst) -> np.ndarray:
    """Pure-Python twin of the sanitizer's `on` policy for dense ids:
    keep mask (the fuzz contract utils/sanitize must match)."""
    keep = []
    for s, d in zip(src.tolist(), dst.tolist()):
        keep.append(0 <= s < VB and 0 <= d < VB)
    return np.array(keep, bool)


def main() -> int:
    rng = np.random.default_rng(42)
    streams = {}
    for i in range(N_TENANTS):
        tid = "t%d" % i
        s, d = make_stream(NUM_W * EB, VB, seed=100 + i)
        streams[tid] = (s.astype(np.int64), d.astype(np.int64))

    # fault-free oracle: each healthy tenant through its own engine
    want = {}
    for tid, (s, d) in streams.items():
        if tid == HOSTILE:
            continue
        eng = StreamSummaryEngine(edge_bucket=EB, vertex_bucket=VB)
        eng.reset()
        want[tid] = digest(eng.process(s, d))

    with tempfile.TemporaryDirectory(prefix="gs-poison-smoke-") as wd:
        dlq_dir = os.path.join(wd, "dlq")
        prev = {k: os.environ.get(k)
                for k in ("GS_SANITIZE", "GS_DLQ_DIR")}
        os.environ["GS_SANITIZE"] = "on"
        os.environ["GS_DLQ_DIR"] = dlq_dir
        try:
            sanitize.reset()
            cohort = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
            for tid in streams:
                cohort.admit(tid)

            # the dispatch poison rides the hostile tenant: any
            # cohort batch containing it fails typed until the
            # bulkhead isolates it (bisect → quarantine)
            def poison(payload):
                if payload and HOSTILE in payload:
                    raise faults.InjectedFault(
                        "hostile tenant poisons the dispatch",
                        "cohort_dispatch")
                return payload

            hostile_rng = np.random.default_rng(7)
            expected_rejects = []  # (offset, src, dst) oracle
            hostile_off = 0
            got = {}
            with faults.inject(faults.FaultSpec(
                    site="cohort_dispatch", action="call", fn=poison,
                    times=10 ** 6)):
                for w in range(NUM_W):
                    for tid, (s, d) in sorted(streams.items()):
                        if tid == HOSTILE:
                            hs, hd, _ts = native.parse_edge_bytes(
                                hostile_bytes(hostile_rng))
                            keep = oracle_filter(hs, hd)
                            for j in np.flatnonzero(~keep):
                                expected_rejects.append(
                                    (hostile_off + int(j),
                                     int(hs[j]), int(hd[j])))
                            hostile_off += len(hs)
                            cohort.feed(tid, hs, hd)
                        else:
                            cohort.feed(tid, s[w * EB:(w + 1) * EB],
                                        d[w * EB:(w + 1) * EB])
                    for k, v in cohort.pump().items():
                        got.setdefault(k, []).extend(v)

            if cohort.tenant_tier(HOSTILE) != "quarantined":
                print("poison smoke FAILED: hostile tenant not "
                      "quarantined (tier=%s)"
                      % cohort.tenant_tier(HOSTILE))
                return 1
            for tid in sorted(want):
                have = digest(got.get(tid, []))
                if have != want[tid] \
                        or len(got.get(tid, [])) != NUM_W:
                    print("poison smoke FAILED: healthy tenant %s "
                          "diverged (%s != %s, %d windows)"
                          % (tid, have, want[tid],
                             len(got.get(tid, []))))
                    return 1

            # every rejected record recoverable from the DLQ
            info = sanitize.scan(dlq_dir)
            from tools.dlq_report import gather, make_fix, reinject
            per = gather(dlq_dir)
            rec = per.get(HOSTILE)
            recovered = (set() if rec is None else
                         set(zip(rec[0].tolist(), rec[1].tolist(),
                                 rec[2].tolist())))
            if recovered != set(expected_rejects) \
                    or info["edges"] != len(expected_rejects):
                print("poison smoke FAILED: DLQ holds %d edge(s), "
                      "oracle expected %d (content match: %s)"
                      % (info["edges"], len(expected_rejects),
                         recovered == set(expected_rejects)))
                return 1

            # replay-exact re-injection after the operator fix
            fix = make_fix("mod:%d" % VB)
            fixed = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
            fixed.admit(HOSTILE)
            counts = reinject(dlq_dir, fixed.feed, fix=fix)
            reinjected = fixed.close(HOSTILE)
            offs, rs, rd, _reasons = per[HOSTILE]
            fs, fd = fix(rs, rd)
            direct = TenantCohort(edge_bucket=EB, vertex_bucket=VB)
            direct.admit(HOSTILE)
            direct.feed(HOSTILE, fs, fd)
            if digest(reinjected) != digest(direct.close(HOSTILE)) \
                    or counts.get(HOSTILE) != len(expected_rejects):
                print("poison smoke FAILED: re-injection is not "
                      "replay-exact (%s)" % counts)
                return 1
        finally:
            sanitize.reset()
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    print("poison smoke ok: 7 healthy tenants bit-identical, hostile "
          "quarantined, %d rejected edge(s) recovered + re-injected "
          "replay-exact" % len(expected_rejects))
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env bash
# One-command CI gate: everything a PR must hold green, in the order
# that fails fastest on the cheapest signal after the test suite.
#
#   1. tier-1 pytest (ROADMAP.md's verify command, CPU backend)
#   2. gslint clean (no non-baselined findings, README in sync)
#   3. perf_schema over every committed PERF*/BENCH_* evidence file
#      (PERF files validate section shapes; BENCH files validate the
#      capture shape)
#   4. bench_compare --baseline BENCH_r05.json self-compare (the
#      regression sentry's wiring smoke: must exit 0 on an unchanged
#      baseline)
#   5. tenancy parity smoke (tools/tenancy_ab.py --smoke): a 1-tenant
#      cohort must be digest-identical to the single-stream engine,
#      so the vmapped cohort path can't silently drift from the
#      single-stream semantics
#   6. serve parity smoke (tools/serve_smoke.py): one tenant fed
#      through a real loopback socket into the journal-armed
#      StreamServer (feed -> pump -> graceful drain) must be
#      digest-identical to the direct cohort feed, with a sealed
#      journal — the wire/durability layer changes availability,
#      never results
#   7. pallas megakernel smoke (tools/pallas_smoke.py): one window
#      through the interpret-mode fused window megakernel must be
#      digest-identical to the XLA fused scan, so Pallas API drift
#      is caught without a chip
#   8. latency-plane smoke (tools/latency_smoke.py): an armed
#      loopback serve run must deliver rows with latency_s, populate
#      the /healthz `latency` section, and leave a ledger whose
#      per-window stage waterfalls SUM to the measured ingest→deliver
#      end-to-end within 5% (tools/latency_report.py exits non-zero
#      otherwise) — at summaries digest-identical to a disarmed run
#   9. poison-input smoke (tools/poison_smoke.py): an 8-tenant cohort
#      with one hostile tenant flooding garbage — the 7 healthy
#      tenants' digests stay bit-identical to a fault-free oracle,
#      the hostile stream is quarantined, and every rejected edge is
#      recoverable from (and replay-exactly re-injectable out of) the
#      dead-letter journal
#  10. cohort-resident smoke (tools/tenancy_ab.py --resident-smoke):
#      a 2-tenant cohort pinned GS_COHORT_RESIDENT=on must be
#      digest-identical to two single-stream engines AND must have
#      actually dispatched through the donated stacked-carry
#      super-batch program (resident_dispatches > 0) — a silent
#      decline to the scan tier fails the gate instead of passing
#      vacuously
#  11. async-pump smoke (tools/pump_smoke.py): a GS_PUMP=async
#      loopback run must be digest-identical per tenant to the sync
#      single-lock legacy AND must actually overlap ingest with
#      dispatch (overlap_feeds > 0, forced deterministically by a
#      hung dispatch) — a pump that quietly serializes fails; plus
#      the sliding default pin (slide == edge_bucket ≡ tumbling)
#  12. windowed-GNN smoke (tools/gnn_smoke.py): one GNN round through
#      the device engine AND the interpret-mode fused Pallas kernel
#      must leave a feature slab + summary stream bit-identical to
#      the numpy lattice twin — a broken lattice edit or a silently
#      refused kernel probe fails the gate instead of passing
#      vacuously
#  13. provenance smoke (tools/provenance_smoke.py): an armed
#      8-tenant cohort run must leave a provenance ledger in which
#      EVERY record — one per finalized window — replays digest-exact
#      through tools/replay_window.py on both the host twin and the
#      fused scan tier (checkpoint + WAL span + recompute); a missing
#      or unreplayable record fails, never silently skips
#
# Usage: tools/ci_check.sh [--skip-tests]
#   --skip-tests  run only the static/evidence gates (seconds, not
#                 minutes) — for pre-commit iteration; CI runs full.
set -euo pipefail
cd "$(dirname "$0")/.."

if [[ "${1:-}" != "--skip-tests" ]]; then
  echo "== [1/13] tier-1 pytest (JAX_PLATFORMS=cpu, -m 'not slow') =="
  JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
else
  echo "== [1/13] tier-1 pytest SKIPPED (--skip-tests) =="
fi

echo "== [2/13] gslint =="
python -m tools.gslint

echo "== [3/13] perf_schema: committed PERF*/BENCH_* evidence =="
evidence=(PERF*.json BENCH_*.json logs/CHAOS_*.json)
python tools/perf_schema.py "${evidence[@]}"

echo "== [4/13] bench_compare self-compare (BENCH_r05.json) =="
python tools/bench_compare.py --baseline BENCH_r05.json > /dev/null

echo "== [5/13] tenancy parity smoke (1-tenant cohort ≡ single stream) =="
JAX_PLATFORMS=cpu python tools/tenancy_ab.py --smoke

echo "== [6/13] serve parity smoke (loopback + drain ≡ direct feed) =="
JAX_PLATFORMS=cpu python tools/serve_smoke.py

echo "== [7/13] pallas megakernel smoke (interpret ≡ XLA fused scan) =="
JAX_PLATFORMS=cpu python tools/pallas_smoke.py

echo "== [8/13] latency-plane smoke (waterfalls reconcile, armed ≡ disarmed) =="
JAX_PLATFORMS=cpu python tools/latency_smoke.py

echo "== [9/13] poison-input smoke (isolation + DLQ replay-exact re-injection) =="
JAX_PLATFORMS=cpu python tools/poison_smoke.py

echo "== [10/13] cohort-resident smoke (resident tier ≡ single streams, no silent decline) =="
JAX_PLATFORMS=cpu python tools/tenancy_ab.py --resident-smoke

echo "== [11/13] async-pump smoke (async ≡ sync, real overlap; sliding pin) =="
JAX_PLATFORMS=cpu python tools/pump_smoke.py

echo "== [12/13] windowed-GNN smoke (device ≡ pallas ≡ numpy lattice twin) =="
JAX_PLATFORMS=cpu python tools/gnn_smoke.py

echo "== [13/13] provenance smoke (every ledger record replays digest-exact on 2 tiers) =="
JAX_PLATFORMS=cpu python tools/provenance_smoke.py

echo "ci_check: all gates green"

#!/usr/bin/env python
"""Resident-tier A/B: does the resident-state window megakernel
(ops/resident_engine.py) beat per-window scan dispatch — and the
chunked scan tier — end-to-end, with EXACT parity?

Two probes, each a JSON row:

  driver_resident — StreamingAnalyticsDriver over the canonical
              524K/32768 row (bench.make_stream): the RESIDENT tier
              (donated super-batch programs + the GS_RESIDENT_SLOTS
              ingest ring) vs the scan tier at its normal chunking vs
              the scan tier forced to ONE dispatch PER WINDOW
              (`_SCAN_CHUNK=1` — the per-window round-trip the
              dispatch wall is made of), plus the native C++ tier
              where the library exports it. Window-by-window sha256
              parity (every snapshot field) asserted before any
              speedup is claimed.
  engine_resident — ResidentSummaryEngine vs StreamSummaryEngine vs
              the same engine at one window per dispatch; summary
              dicts compared exactly.

Timing is median-of-3 with min/max dispersion committed in the row
(the ingress A/B's 1.13x/1.02x flip-flop taught us a single run is
load noise, not evidence). GS_AUTOTUNE is pinned OFF inside the
probes so the residency lever is measured in isolation.

The committed `resident_ab` rows are what ops/resident_engine.
resolve_resident gates on: parity true AND the resident rate ≥1.05×
the best committed alternative (scan AND native) on EVERY driver row,
or the resolved tier stands. `speedup` in the row is resident vs
PER-WINDOW dispatch (the wall the megakernel kills);
`speedup_vs_scan` is the adoption-relevant ratio. Commit policy
identical to tools/egress_ab.py (PERF.json only when backend-matched,
PERF_<backend>.json always).
"""

import hashlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from bench import make_stream  # noqa: E402
from tools.egress_ab import _dispersion, timed_stats  # noqa: E402


def _digest_windows(results) -> list:
    out = []
    for r in results:
        h = hashlib.sha256()
        for a in (r.vertex_ids, r.degrees, r.cc_labels,
                  r.bipartite_odd):
            if a is not None:
                h.update(np.ascontiguousarray(a).tobytes())
        out.append((int(r.window_start), int(r.num_edges),
                    None if r.triangles is None else int(r.triangles),
                    h.hexdigest()[:16]))
    return out


def driver_resident(jax, num_edges, results):
    from gelly_streaming_tpu import native
    from gelly_streaming_tpu.core.driver import StreamingAnalyticsDriver
    from gelly_streaming_tpu.ops import resident_engine

    eb, vb = 32768, 65536
    src, dst = make_stream(num_edges, vb)

    def build(tier):
        return StreamingAnalyticsDriver(
            window_ms=0, edge_bucket=eb, vertex_bucket=vb,
            analytics=("degrees", "cc", "bipartite"),
            snapshot_tier=tier)

    drivers = {"resident": build("resident"), "scan": build("scan"),
               "perwindow": build("scan")}
    drivers["perwindow"]._SCAN_CHUNK = 1  # one dispatch per window
    if native.snapshot_available():
        drivers["native"] = build("native")
    digests = {}
    for name, drv in drivers.items():
        digests[name] = _digest_windows(drv.run_arrays(src, dst))
        drv.reset()
    parity = all(d == digests["scan"] for d in digests.values())

    stats = {}
    for name, drv in drivers.items():
        def run(drv=drv):
            drv.reset()
            drv.run_arrays(src, dst)

        stats[name] = timed_stats(run, reps=3, warmup=0)

    row = {
        "probe": "driver_resident",
        "backend": jax.default_backend(),
        "num_edges": len(src), "eb": eb, "vb": vb,
        "superbatch": resident_engine.resident_spb(eb),
        "ring_slots": resident_engine.ring_slots(),
        "donated": resident_engine.donation_supported(),
        "resident_edges_per_s": round(len(src)
                                      / stats["resident"][0]),
        "scan_edges_per_s": round(len(src) / stats["scan"][0]),
        "perwindow_edges_per_s": round(len(src)
                                       / stats["perwindow"][0]),
        "parity": bool(parity),
    }
    if "native" in stats:
        row["native_edges_per_s"] = round(len(src)
                                          / stats["native"][0])
    for name in stats:
        _dispersion(row, name, stats[name])
    if parity:
        row["speedup"] = round(
            stats["perwindow"][0] / stats["resident"][0], 3)
        row["speedup_worst"] = round(
            stats["perwindow"][1] / stats["resident"][2], 3)
        row["speedup_best"] = round(
            stats["perwindow"][2] / stats["resident"][1], 3)
        row["speedup_vs_scan"] = round(
            stats["scan"][0] / stats["resident"][0], 3)
    else:
        print("PARITY FAILURE between snapshot tiers (driver)",
              file=sys.stderr)
    results.append(row)
    print(json.dumps(row), flush=True)


def engine_resident(jax, num_edges, results):
    from gelly_streaming_tpu.ops.resident_engine import (
        ResidentSummaryEngine)
    from gelly_streaming_tpu.ops.scan_analytics import (
        StreamSummaryEngine)

    eb, vb = 32768, 65536
    src, dst = make_stream(num_edges, vb, seed=5)
    src32, dst32 = src.astype(np.int32), dst.astype(np.int32)

    engines = {
        "resident": ResidentSummaryEngine(edge_bucket=eb,
                                          vertex_bucket=vb),
        "scan": StreamSummaryEngine(edge_bucket=eb, vertex_bucket=vb),
        "perwindow": StreamSummaryEngine(edge_bucket=eb,
                                         vertex_bucket=vb),
    }
    engines["perwindow"].MAX_WINDOWS = 1  # one dispatch per window
    outs = {}
    for name, eng in engines.items():
        outs[name] = eng.process(src32, dst32)
        eng.reset()
    parity = all(o == outs["scan"] for o in outs.values())

    stats = {}
    for name, eng in engines.items():
        def run(eng=eng):
            eng.reset()
            eng.process(src32, dst32)

        stats[name] = timed_stats(run, reps=3, warmup=0)

    row = {
        "probe": "engine_resident",
        "backend": jax.default_backend(),
        "num_edges": len(src), "eb": eb, "vb": vb,
        "ingress": engines["resident"].ingress,
        "superbatch": engines["resident"].MAX_WINDOWS,
        "resident_edges_per_s": round(len(src)
                                      / stats["resident"][0]),
        "scan_edges_per_s": round(len(src) / stats["scan"][0]),
        "perwindow_edges_per_s": round(len(src)
                                       / stats["perwindow"][0]),
        "parity": bool(parity),
    }
    for name in stats:
        _dispersion(row, name, stats[name])
    if parity:
        row["speedup"] = round(
            stats["perwindow"][0] / stats["resident"][0], 3)
        row["speedup_worst"] = round(
            stats["perwindow"][1] / stats["resident"][2], 3)
        row["speedup_best"] = round(
            stats["perwindow"][2] / stats["resident"][1], 3)
        row["speedup_vs_scan"] = round(
            stats["scan"][0] / stats["resident"][0], 3)
    else:
        print("PARITY FAILURE between summary engines",
              file=sys.stderr)
    results.append(row)
    print(json.dumps(row), flush=True)


PROBE_NAMES = ("driver_resident", "engine_resident")


def commit_results(results, backend: str) -> None:
    """Merge this run's `resident_ab` rows into the committed evidence
    — the same policy as tools/egress_ab.py: PERF.json only when its
    backend label matches the live backend, the per-backend archive
    PERF_<backend>.json always."""
    targets = ((os.path.join(REPO, "PERF.json"), True),
               (os.path.join(REPO, "PERF_%s.json" % backend), False))
    for path, need_match in targets:
        try:
            with open(path) as f:
                cur = json.load(f)
        except (OSError, ValueError):
            cur = {}
        if need_match and cur.get("backend") != backend:
            print("not committing to %s: file backend %r != live %r"
                  % (os.path.basename(path), cur.get("backend"),
                     backend), file=sys.stderr)
            continue
        cur.setdefault("backend", backend)
        cur["resident_ab"] = results
        with open(path, "w") as f:
            json.dump(cur, f, indent=2)
        print("committed %s row(s) to %s"
              % (len(results), os.path.basename(path)), flush=True)


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("probes", nargs="*",
                    help="subset of %s to run (default: all)"
                         % (PROBE_NAMES,))
    ap.add_argument("--edges", type=int,
                    default=int(os.environ.get("GS_AB_EDGES", 524_288)))
    ap.add_argument("--commit", action="store_true",
                    help="merge rows into PERF.json (backend-matched) "
                         "and PERF_<backend>.json")
    args = ap.parse_args()
    bad = [p for p in args.probes if p not in PROBE_NAMES]
    if bad:
        ap.error("unknown probe(s) %s; valid: %s"
                 % (bad, list(PROBE_NAMES)))
    want = args.probes or list(PROBE_NAMES)

    # measure the residency lever in isolation: the online tuner
    # changing dispatch knobs between reps would be noise here
    os.environ["GS_AUTOTUNE"] = "0"

    import jax

    results = []
    if "driver_resident" in want:
        driver_resident(jax, args.edges, results)
    if "engine_resident" in want:
        engine_resident(jax, args.edges, results)
    out = os.path.join(REPO, "logs",
                       "resident_ab_%s.json" % jax.default_backend())
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print("wrote %s" % out, flush=True)
    if args.commit:
        commit_results(results, jax.default_backend())


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Ranked per-tenant placement advisor over the tenant observatory.

Joins the three tenant-grain planes one operator decision needs —
*who is burning the device* (per-tenant cost attribution:
`gs_tenant_device_seconds` / `gs_tenant_attributed_bytes`,
utils/metrics.attribute_dispatch), *who is hurting* (the latency
plane's per-tenant e2e p50/p95/p99, SLO burn rate, queue depth+age),
and *who has history* (durable `quarantine` events in the telemetry
ledger + the cohort's live quarantined list) — into one ranked table
and a JSON document a fleet router can consume to decide which tenant
to move first (pair with tools/replay_window.py to prove the move was
bit-exact).

Input is a `/healthz` body: a URL (fetched), a file path, or `-`
(stdin) — the sections used are `tenants` (attribution rows),
`hot_tenants` (the server-side top-K score), `latency.tenants`, and
`serve.queues`/`serve.quarantined` when the serving layer is up.
Quarantine HISTORY needs the flight-recorder ledger
(GS_TRACE_DIR/events.jsonl): pass `--events` to count per-tenant
`quarantine` records and surface the last reason.

Usage:
  python tools/tenant_report.py --healthz http://127.0.0.1:9100/healthz
  python tools/tenant_report.py --healthz snap.json --events ledger.jsonl \
      [--top 10] [--json]

Exit status: 0 = report rendered, 2 = no tenant data in the body.
"""
from __future__ import annotations

import argparse
import json
import sys


def load_body(src: str) -> dict:
    if src == "-":
        return json.load(sys.stdin)
    if src.startswith("http://") or src.startswith("https://"):
        from urllib.request import urlopen

        with urlopen(src, timeout=10) as r:
            return json.loads(r.read().decode())
    with open(src) as f:
        return json.load(f)


def quarantine_history(events_path: str) -> dict:
    """{tenant: {"count", "last_reason", "last_windows_done"}} from
    the ledger's durable `quarantine` events (torn final line
    tolerated — the telemetry reader discipline)."""
    hist = {}
    with open(events_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail
            if rec.get("t") != "event" \
                    or rec.get("name") != "quarantine":
                continue
            a = rec.get("a") or {}
            tid = str(a.get("tenant"))
            h = hist.setdefault(tid, {"count": 0, "last_reason": None,
                                      "last_windows_done": None})
            h["count"] += 1
            h["last_reason"] = a.get("reason")
            h["last_windows_done"] = a.get("windows_done")
    return hist


def build_report(body: dict, hist=None, top: int = 0) -> dict:
    """The placement table: one row per tenant, ranked by the
    hot-tenant score (server-side when the body carries
    `hot_tenants`, else recomputed from device-seconds share)."""
    tenants = body.get("tenants") or {}
    lat = (body.get("latency") or {}).get("tenants") or {}
    serve = body.get("serve") or {}
    queues = serve.get("queues") or {}
    quarantined = set(serve.get("quarantined") or ())
    hot = {r["tenant"]: r for r in body.get("hot_tenants") or ()}
    hist = hist or {}

    total_s = sum(float(v.get("device_s") or 0.0)
                  for v in tenants.values())
    rows = []
    for tid in sorted(set(tenants) | set(lat) | set(hot)):
        t = tenants.get(tid) or {}
        l = lat.get(tid) or {}
        h = hot.get(tid) or {}
        q = queues.get(tid) or {}
        dev_s = float(t.get("device_s") or h.get("device_s") or 0.0)
        share = (dev_s / total_s) if total_s > 0 else 0.0
        score = h.get("score")
        if score is None:
            score = share  # body predates hot_tenants: share-ranked
        qh = hist.get(tid) or {}
        rows.append({
            "tenant": tid,
            "score": round(float(score), 6),
            "device_share": round(share, 6),
            "device_s": round(dev_s, 6),
            "attr_bytes": int(t.get("attr_bytes")
                              or h.get("attr_bytes") or 0),
            "tier": t.get("tier") or h.get("tier"),
            "windows": t.get("windows") or l.get("windows"),
            "e2e_p99_s": l.get("e2e_p99_s"),
            "burn_rate": h.get("burn_rate"),
            "queue_edges": q.get("edges"),
            "queue_age_s": q.get("age_s") or h.get("queue_age_s"),
            "quarantined": tid in quarantined,
            "quarantines": int(qh.get("count") or 0),
            "last_quarantine_reason": qh.get("last_reason"),
        })
    rows.sort(key=lambda r: (-r["score"], r["tenant"]))
    if top:
        rows = rows[:top]
    return {
        "status": body.get("status"),
        "total_device_s": round(total_s, 6),
        "tenants": rows,
    }


def render(rep: dict) -> str:
    cols = ("tenant", "score", "dev%", "device_s", "MBytes",
            "p99_s", "burn", "q_edges", "q_age_s", "tier", "quar")
    lines = ["%-12s %7s %6s %9s %8s %8s %6s %8s %8s %-10s %s"
             % cols]
    for r in rep["tenants"]:
        def f(v, fmt="%s"):
            return "-" if v is None else fmt % v
        quar = ("NOW" if r["quarantined"]
                else str(r["quarantines"]) if r["quarantines"]
                else "-")
        lines.append(
            "%-12s %7.3f %5.1f%% %9.4f %8s %8s %6s %8s %8s %-10s %s"
            % (r["tenant"][:12], r["score"],
               100.0 * r["device_share"], r["device_s"],
               f(round(r["attr_bytes"] / 1e6, 1) if r["attr_bytes"]
                 else None),
               f(r["e2e_p99_s"], "%.4f"), f(r["burn_rate"], "%.2f"),
               f(r["queue_edges"]), f(r["queue_age_s"], "%.3f"),
               f(r["tier"]), quar))
    lines.append("total attributed device seconds: %.4f"
                 % rep["total_device_s"])
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="ranked per-tenant placement advisor "
                    "(cost attribution x latency x quarantine)")
    ap.add_argument("--healthz", required=True,
                    help="/healthz URL, JSON file path, or '-'")
    ap.add_argument("--events", default=None,
                    help="telemetry events.jsonl for quarantine "
                         "history")
    ap.add_argument("--top", type=int, default=0,
                    help="limit to the K hottest tenants")
    ap.add_argument("--json", action="store_true",
                    help="emit the router-consumable JSON document")
    args = ap.parse_args(argv)

    body = load_body(args.healthz)
    hist = quarantine_history(args.events) if args.events else None
    rep = build_report(body, hist=hist, top=args.top)
    if not rep["tenants"]:
        print("no tenant data in the /healthz body (is GS_METRICS=1 "
              "set, and has a window finalized?)", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(rep, indent=2, sort_keys=True))
    else:
        print(render(rep))
    return 0


if __name__ == "__main__":
    sys.exit(main())
